"""Generation: jitted greedy/sampling/beam decode (paddle_tpu.generation)
and the reference-shaped BeamSearchDecoder/dynamic_decode/gather_tree API
(reference: fluid/layers/rnn.py:1, operators/math/beam_search.cc:1 — here
cross-checked against numpy oracles and the eager no-cache forward)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import models, nn
from paddle_tpu.core.tensor import Tensor, unwrap


def tiny_gpt(vocab=13, hidden=16, layers=2, heads=2, max_pos=64):
    cfg = models.GPTConfig(vocab_size=vocab, hidden_size=hidden,
                           num_hidden_layers=layers,
                           num_attention_heads=heads,
                           hidden_dropout_prob=0.0,
                           attention_probs_dropout_prob=0.0,
                           max_position_embeddings=max_pos)
    paddle.seed(7)
    m = models.GPTForPretraining(cfg)
    m.eval()
    return m


def eager_logits(model, ids_np):
    """Full no-cache forward -> last-position logits (the oracle path)."""
    out = model(paddle.to_tensor(ids_np.astype("int32")))
    return np.asarray(unwrap(out))[:, -1, :].astype(np.float64)


def test_gather_tree_matches_numpy():
    rng = np.random.RandomState(0)
    t, b, k = 5, 2, 3
    ids = rng.randint(0, 9, (t, b, k))
    parents = rng.randint(0, k, (t, b, k))
    got = np.asarray(unwrap(nn.gather_tree(
        paddle.to_tensor(ids), paddle.to_tensor(parents))))

    # backtrack oracle: lane ki follows parents from the last step back
    want = np.zeros_like(ids)
    for bi in range(b):
        for ki in range(k):
            beam = ki
            for ti in reversed(range(t)):
                want[ti, bi, ki] = ids[ti, bi, beam]
                beam = parents[ti, bi, beam]
    assert (got == want).all()


def test_generate_greedy_matches_eager_argmax():
    model = tiny_gpt()
    rng = np.random.RandomState(1)
    prompt = rng.randint(0, 13, (2, 4))
    max_new = 6

    out, scores = model.generate(paddle.to_tensor(prompt.astype("int32")),
                                 max_new_tokens=max_new)
    got = np.asarray(unwrap(out))

    seq = prompt.copy()
    want = []
    for _ in range(max_new):
        nxt = eager_logits(model, seq).argmax(-1)
        want.append(nxt)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    want = np.stack(want, axis=1)
    assert (got == want).all(), (got, want)
    assert np.asarray(unwrap(scores)).shape == (2,)


def test_generate_eos_padding_and_score():
    model = tiny_gpt()
    prompt = np.array([[1, 2, 3]], dtype="int32")
    # pick eos = the greedy first token so generation finishes immediately
    first = int(eager_logits(model, prompt).argmax(-1)[0])
    out, scores = model.generate(paddle.to_tensor(prompt),
                                 max_new_tokens=5, eos_token_id=first,
                                 pad_token_id=0)
    got = np.asarray(unwrap(out))[0]
    assert got[0] == first and (got[1:] == 0).all()


def test_generate_all_finished_early_exit_parity():
    """The scan body skips the model call via lax.cond once every row is
    finished (short completions inside a long max_new_tokens budget stop
    paying decode FLOPs).  Output contract is unchanged: same tokens, pad
    after eos, same scores as a small-budget run of the same prompt."""
    model = tiny_gpt()
    prompt = np.array([[1, 2, 3], [4, 5, 6]], dtype="int32")
    # eos = each row's greedy first token => all rows finished after step 1;
    # rows disagree, so pick row 0's and let row 1 run to its own eos/pad
    first = int(eager_logits(model, prompt).argmax(-1)[0])
    out, scores = model.generate(paddle.to_tensor(prompt),
                                 max_new_tokens=24, eos_token_id=first,
                                 pad_token_id=0)
    got = np.asarray(unwrap(out))
    # oracle: step the eager forward until every row has hit eos
    seq = prompt.copy()
    want = np.zeros_like(got)
    finished = np.zeros(2, bool)
    for t in range(24):
        nxt = eager_logits(model, seq).argmax(-1)
        nxt = np.where(finished, 0, nxt)
        want[:, t] = nxt
        finished |= nxt == first
        seq = np.concatenate([seq, nxt[:, None].astype("int32")], axis=1)
        if finished.all():
            break
    assert (got == want).all(), (got, want)
    # the finished rows' scores stop accumulating after their eos
    short_out, short_scores = model.generate(
        paddle.to_tensor(prompt), max_new_tokens=4, eos_token_id=first,
        pad_token_id=0)
    if bool((np.asarray(unwrap(short_out)) == got[:, :4]).all()) and \
            finished.all():
        np.testing.assert_allclose(np.asarray(unwrap(scores)),
                                   np.asarray(unwrap(short_scores)),
                                   rtol=1e-5, atol=1e-6)


def test_generate_topk1_matches_greedy_and_seeded_sampling_reproducible():
    model = tiny_gpt()
    prompt = np.array([[3, 1], [2, 5]], dtype="int32")
    g, _ = model.generate(paddle.to_tensor(prompt), max_new_tokens=5)
    s1, _ = model.generate(paddle.to_tensor(prompt), max_new_tokens=5,
                           decode_strategy="sampling", top_k=1, seed=0)
    assert (np.asarray(unwrap(g)) == np.asarray(unwrap(s1))).all()
    a, _ = model.generate(paddle.to_tensor(prompt), max_new_tokens=5,
                          decode_strategy="sampling", top_k=4, seed=3)
    b, _ = model.generate(paddle.to_tensor(prompt), max_new_tokens=5,
                          decode_strategy="sampling", top_k=4, seed=3)
    assert (np.asarray(unwrap(a)) == np.asarray(unwrap(b))).all()


def test_top_p_filter_keeps_nucleus():
    from paddle_tpu.generation import apply_top_p
    logits = jnp.log(jnp.array([[0.5, 0.3, 0.15, 0.05]]))
    out = np.asarray(apply_top_p(logits, 0.7))
    assert np.isfinite(out[0, 0]) and np.isfinite(out[0, 1])
    assert out[0, 2] <= -1e8 and out[0, 3] <= -1e8


def test_dynamic_sampling_helpers_match_static():
    """The per-row traced variants (the serving decode step's shared-trace
    path) must agree with the static helpers row by row, including the
    k=0 / p=1 disabled encodings."""
    from paddle_tpu.generation import (apply_top_k, apply_top_p,
                                       apply_top_k_dynamic,
                                       apply_top_p_dynamic,
                                       process_logits_dynamic,
                                       _process_logits)
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(4, 9).astype("float32"))
    for k in (0, 1, 3, 9):
        dyn = apply_top_k_dynamic(logits, jnp.full((4,), k, jnp.int32))
        np.testing.assert_allclose(np.asarray(dyn),
                                   np.asarray(apply_top_k(logits, k)))
    for p in (0.3, 0.7, 1.0):
        dyn = apply_top_p_dynamic(logits, jnp.full((4,), p, jnp.float32))
        np.testing.assert_allclose(np.asarray(dyn),
                                   np.asarray(apply_top_p(logits, p)))
    # per-row heterogeneity: each row filtered under its own params
    temp = jnp.array([1.0, 0.7, 1.3, 1.0], jnp.float32)
    top_k = jnp.array([0, 3, 0, 2], jnp.int32)
    top_p = jnp.array([1.0, 1.0, 0.8, 0.9], jnp.float32)
    greedy = jnp.array([True, False, False, False])
    out = np.asarray(process_logits_dynamic(logits, temp, top_k, top_p,
                                            greedy))
    np.testing.assert_allclose(out[0], np.asarray(logits)[0])  # greedy raw
    for i in (1, 2, 3):
        want = _process_logits(logits[i:i + 1], float(temp[i]),
                               int(top_k[i]), float(top_p[i]), False)
        np.testing.assert_allclose(out[i], np.asarray(want)[0], rtol=1e-6)


def _numpy_beam(model, prompt, k, max_new, eos, pad):
    """Beam-search oracle over the eager no-cache forward."""
    b = prompt.shape[0]
    beams = [[prompt[i].tolist() for _ in range(k)] for i in range(b)]
    scores = np.tile(np.array([0.0] + [-1e9] * (k - 1)), (b, 1))
    finished = np.zeros((b, k), bool)
    toks_out = [[[] for _ in range(k)] for _ in range(b)]
    for _ in range(max_new):
        flat = np.array([beams[i][j] for i in range(b) for j in range(k)])
        logits = eager_logits(model, flat)
        logp = logits - np.log(np.exp(
            logits - logits.max(-1, keepdims=True)).sum(-1, keepdims=True)) \
            - logits.max(-1, keepdims=True)
        logp = logp.reshape(b, k, -1)
        v = logp.shape[-1]
        fin_row = np.full((v,), -1e9); fin_row[pad] = 0.0
        logp = np.where(finished[:, :, None], fin_row[None, None], logp)
        cand = scores[:, :, None] + logp
        new_beams, new_out = [], []
        for i in range(b):
            order = np.argsort(-cand[i].reshape(-1), kind="stable")[:k]
            par, tok = order // v, order % v
            scores[i] = cand[i].reshape(-1)[order]
            nb, no = [], []
            nf = []
            for j in range(k):
                p, t = int(par[j]), int(tok[j])
                was_fin = finished[i, p]
                t_eff = pad if was_fin else t
                nb.append(beams[i][p] + [t_eff])
                no.append(toks_out[i][p] + [t_eff])
                nf.append(bool(was_fin or t_eff == eos))
            new_beams.append(nb); new_out.append(no)
            finished[i] = nf
        beams, toks_out = new_beams, new_out
    best = scores.argmax(1)
    return np.array([toks_out[i][best[i]] for i in range(b)]), \
        scores[np.arange(b), best]


def test_generate_beam_matches_numpy_oracle():
    model = tiny_gpt()
    rng = np.random.RandomState(2)
    prompt = rng.randint(0, 13, (2, 3)).astype("int32")
    out, sc = model.generate(paddle.to_tensor(prompt), max_new_tokens=5,
                             decode_strategy="beam_search", num_beams=3,
                             eos_token_id=12, pad_token_id=0)
    want, want_sc = _numpy_beam(model, prompt, 3, 5, eos=12, pad=0)
    assert (np.asarray(unwrap(out)) == want).all(), \
        (np.asarray(unwrap(out)), want)
    assert np.allclose(np.asarray(unwrap(sc)), want_sc, atol=1e-3)


def test_beam_decoder_dynamic_decode_gru():
    """BeamSearchDecoder over a GRU cell + embedding + projection, checked
    against a numpy beam oracle that drives the same cell eagerly."""
    hidden, vocab, k = 8, 7, 3
    paddle.seed(11)
    cell = nn.GRUCell(hidden, hidden)
    emb = nn.Embedding(vocab, hidden)
    proj = nn.Linear(hidden, vocab)
    dec = nn.BeamSearchDecoder(cell, start_token=1, end_token=2, beam_size=k,
                               embedding_fn=emb, output_fn=proj)
    b = 2
    rng = np.random.RandomState(3)
    h0 = paddle.to_tensor(rng.randn(b, hidden).astype("float32"))
    outs, final_states = nn.dynamic_decode(dec, inits=h0, max_step_num=4)
    ids = np.asarray(unwrap(outs))  # (B, T, K) after batch-major swap
    assert ids.shape[0] == b and ids.shape[2] == k

    # oracle: greedy-beam over the same cell called eagerly
    def step_cell(tok, h):
        x = emb(paddle.to_tensor(tok.astype("int32")))
        out, nh = cell(x, paddle.to_tensor(h.astype("float32")))
        logits = proj(out)
        return np.asarray(unwrap(logits)).astype(np.float64), \
            np.asarray(unwrap(nh))

    h = np.repeat(np.asarray(unwrap(h0)), k, axis=0)
    scores = np.tile(np.array([0.0] + [-1e9] * (k - 1)), (b, 1))
    finished = np.zeros((b, k), bool)
    tok = np.full((b * k,), 1)
    seqs = [[[] for _ in range(k)] for _ in range(b)]
    for _ in range(4):
        logits, h = step_cell(tok, h)
        logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
        logp = logp.reshape(b, k, vocab)
        fin_row = np.full((vocab,), -1e9); fin_row[2] = 0.0
        logp = np.where(finished[:, :, None], fin_row[None, None], logp)
        cand = scores[:, :, None] + logp
        new_h = np.empty_like(h.reshape(b, k, hidden))
        ntok = np.empty((b, k), int)
        for i in range(b):
            order = np.argsort(-cand[i].reshape(-1), kind="stable")[:k]
            par, t = order // vocab, order % vocab
            scores[i] = cand[i].reshape(-1)[order]
            nf, ns = [], []
            for j in range(k):
                p = int(par[j])
                ns.append(seqs[i][p] + [int(t[j])])
                nf.append(bool(finished[i, p] or t[j] == 2))
                new_h[i, j] = h.reshape(b, k, hidden)[i, p]
                ntok[i, j] = int(t[j])
            seqs[i] = ns
            finished[i] = nf
        h = new_h.reshape(b * k, hidden)
        tok = ntok.reshape(-1)
        if finished.all():
            break
    t_got = ids.shape[1]
    for i in range(b):
        for j in range(k):
            assert ids[i, :, j].tolist() == seqs[i][j][:t_got], \
                (i, j, ids[i, :, j], seqs[i][j])
