"""Network-transparent fleet (ISSUE 15): standalone remote TCP workers
(`--listen`), sha256-verified weight shipping over the attach handshake,
beat-frame wedge fencing with no heartbeat file, epoch-fenced reconnect,
submit dedup under ack loss, and the PDTPU_FAULT_NET_* chaos knobs.

Tier-1 keeps every test to <= 2 workers on the tiny GPT over loopback
TCP with a hard SIGALRM per-test timeout (a hung or partitioned worker
can never wedge the suite); the partition/chaos matrix runs under
`slow`.
"""
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit, models
from paddle_tpu.serving import (FleetRouter, RestartBackoff, ServingEngine,
                                WireFormatError, WorkerDiedError)
from paddle_tpu.serving.fleet import RemoteReplica
from paddle_tpu.serving.worker import (RemoteWorkerClient, StaleEpochError,
                                       _FrameConn, _WorkerServer)
from paddle_tpu.utils import faults

pytestmark = pytest.mark.remote_fleet

GPT_KW = dict(vocab_size=64, hidden_size=32, num_hidden_layers=2,
              num_attention_heads=2, hidden_dropout_prob=0.0,
              attention_probs_dropout_prob=0.0,
              max_position_embeddings=128)
ENGINE_KW = dict(max_slots=2, max_len=64, prefill_buckets=(8,),
                 decode_chunk=2)

# the spec's FACTORY seed deliberately differs from the shipped-weight
# seed: bit-identical output against the seed-99 oracle proves the
# worker serves the SHIPPED artifact, not a seeded rebuild
FACTORY_SEED, WEIGHT_SEED = 11, 99


def remote_spec(weights=None, **engine_overrides):
    ekw = dict(ENGINE_KW, **engine_overrides)
    ekw["prefill_buckets"] = list(ekw["prefill_buckets"])
    spec = {"model": {"factory": "paddle_tpu.serving.worker:build_gpt",
                      "kwargs": dict(GPT_KW, seed=FACTORY_SEED)},
            "engine": ekw}
    if weights is not None:
        spec["weights"] = weights
    return spec


def tiny_model(seed=WEIGHT_SEED):
    paddle.seed(seed)
    m = models.GPTForPretraining(models.GPTConfig(**GPT_KW))
    m.eval()
    return m


def oracle(model, prompt, max_new):
    out, _ = model.generate(paddle.to_tensor(np.asarray(prompt)[None]),
                            max_new_tokens=max_new)
    return np.asarray(out.numpy())[0].tolist()


@pytest.fixture
def shipped_weights(tmp_path):
    """A real jit.save weight artifact for the seed-99 model."""
    m = tiny_model(WEIGHT_SEED)
    jit.save(m, str(tmp_path / "m"))
    path = str(tmp_path / "m.pdiparams.npz")
    assert os.path.exists(path)
    return m, path


@pytest.fixture
def hard_timeout():
    """Tier-1 wedge guard: SIGALRM aborts the test outright if a remote
    hang ever leaks past the in-test timeouts."""
    def handler(signum, frame):
        raise TimeoutError("remote_fleet hard per-test timeout (a remote "
                           "worker hang leaked past the in-test timeouts)")
    old = signal.signal(signal.SIGALRM, handler)
    signal.alarm(150)
    yield
    signal.alarm(0)
    signal.signal(signal.SIGALRM, old)


@pytest.fixture
def fleet_guard():
    """Closes every registered fleet/client at teardown — a failing test
    leaves no orphan connection behind."""
    items = []
    yield items.append
    for item in items:
        try:
            item.close()
        except Exception:
            pass
    faults.reset()


@pytest.fixture
def remote_worker():
    """Factory spawning standalone `--listen` workers on an ephemeral
    loopback port; yields (address, proc) and reaps at teardown."""
    procs = []

    def spawn(index=0):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = (root + os.pathsep + env["PYTHONPATH"]
                             if env.get("PYTHONPATH") else root)
        proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.serving.worker",
             "--listen", "127.0.0.1:0", "--index", str(index)],
            stdin=subprocess.DEVNULL, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, env=env,
            start_new_session=True)
        procs.append(proc)
        while True:  # SIGALRM guards the wait
            line = proc.stdout.readline()
            if not line:
                raise AssertionError(
                    "remote worker exited before listening")
            if "worker listening on" in line:
                addr = line.strip().rsplit(" ", 1)[-1]
                break
        # keep draining stdout so the worker can never block on a full
        # pipe mid-test
        threading.Thread(target=lambda: proc.stdout.read(),
                         daemon=True).start()
        return addr, proc

    yield spawn
    for p in procs:
        try:
            p.kill()
            p.wait(timeout=10)
        except Exception:
            pass


def wait_for(pred, timeout, what):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return
        time.sleep(0.005)
    raise AssertionError(f"timed out after {timeout}s waiting for {what}")


def drive(fleet, pred, timeout, what):
    """Tick the fleet from THIS thread (the driving-thread contract)
    until `pred` holds."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        fleet.step()
        if pred():
            return
        time.sleep(0.002)
    raise AssertionError(f"timed out after {timeout}s waiting for {what}")


def drive_client(client, pred, timeout, what):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        try:
            client.step()
        except (WorkerDiedError, WireFormatError):
            pass  # session torn down under us — pred decides
        if pred():
            return
        time.sleep(0.002)
    raise AssertionError(f"timed out after {timeout}s waiting for {what}")


# ---------------------------------------------------------------------------
# pure wire units: no subprocess, no model
# ---------------------------------------------------------------------------

def test_frameconn_assembly_deadline_and_send_stall():
    """ISSUE-15 satellite: a peer holding ONE frame open forever (the
    slowloris PDTPU_FAULT_NET_DELAY models) trips the typed assembly
    deadline instead of occupying recv_frames; a peer not draining its
    socket trips the bounded-send WorkerDiedError; and an honestly slow
    multi-part send still assembles fine."""
    # 1) partial frame stuck past the assembly deadline -> typed
    a, b = socket.socketpair()
    rx = _FrameConn(b, frame_deadline=0.25)
    a.sendall((1000).to_bytes(8, "big") + b"x" * 10)  # 10/1000 bytes
    t0 = time.monotonic()
    with pytest.raises(WireFormatError, match="assembly deadline"):
        while True:
            rx.recv_frames(0.02)
            assert time.monotonic() - t0 < 5.0, "deadline never tripped"
    a.close()
    rx.close()
    # 2) a frame split across writes with pauses assembles (progress
    #    resets the deadline clock; only a STUCK frame is typed)
    a, b = socket.socketpair()
    rx = _FrameConn(b, frame_deadline=5.0)
    from paddle_tpu.serving.worker import pack_frame
    frame = pack_frame("ping", {"k": 1})
    a.sendall(frame[:9])
    assert rx.recv_frames(0.01) == []
    time.sleep(0.05)
    a.sendall(frame[9:])
    frames = rx.recv_frames(0.2)
    assert len(frames) == 1 and frames[0][0] == "ping"
    a.close()
    rx.close()
    # 3) peer not draining: bounded send raises typed, never hangs.
    #    (partial writes under the deadline are tolerated — the frame is
    #    far larger than the socket buffers, so the send MUST go short
    #    repeatedly before the deadline verdict)
    a, b = socket.socketpair()
    a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 4096)
    b.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
    tx = _FrameConn(a, send_timeout=0.3)
    with pytest.raises(WorkerDiedError, match="stalled"):
        tx.send("blob", {}, {"data": np.zeros(1 << 21, np.uint8)})
    tx.close()
    b.close()


def test_manager_silence_self_abort_and_abort_epoch(hard_timeout):
    """ISSUE-15 satellite: under an injected clock, a remote session
    whose manager went silent past `manager_silence_s` aborts every
    resident/queued run typed (StaleEpochError) and detaches; the
    `abort_epoch` verb does the same but ONLY for its own epoch."""
    engine = ServingEngine(tiny_model(FACTORY_SEED), **ENGINE_KW)
    s_mgr, s_wrk = socket.socketpair()
    conn = _FrameConn(s_wrk)
    now = {"t": 100.0}
    try:
        server = _WorkerServer(engine, conn, None, 0, epoch=3,
                               manager_silence_s=2.0,
                               _clock=lambda: now["t"])
        # a wrong-epoch abort_epoch is a stale manager talking to the
        # wrong session: ignored entirely
        server._handle("abort_epoch", {"epoch": 2}, {})
        assert server.detach is None
        resp = engine.submit(np.arange(1, 5, dtype=np.int32), 4)
        # inside the budget: nothing aborts
        now["t"] = 101.9
        assert not server._check_manager_silence()
        assert resp.error is None
        # past the budget: typed self-abort + detach
        now["t"] = 102.1
        assert server._check_manager_silence()
        assert server.detach == "manager-silence"
        assert isinstance(resp.error, StaleEpochError)
        assert "manager silent" in str(resp.error)
        # matching-epoch abort_epoch on a fresh server also aborts typed
        server2 = _WorkerServer(engine, conn, None, 0, epoch=3,
                                manager_silence_s=None,
                                _clock=lambda: now["t"])
        assert not server2._check_manager_silence()  # no budget, no fence
        resp2 = engine.submit(np.arange(1, 5, dtype=np.int32), 4)
        server2._handle("abort_epoch", {"epoch": 3}, {})
        assert server2.detach == "abort_epoch"
        assert isinstance(resp2.error, StaleEpochError)
        assert "epoch superseded" in str(resp2.error)
    finally:
        conn.close()
        s_mgr.close()
        engine.close()


# ---------------------------------------------------------------------------
# tier-1 remote smoke: <= 2 workers, tiny GPT over loopback TCP
# ---------------------------------------------------------------------------

def test_remote_attach_ships_weights_dedups_and_reattaches(
        hard_timeout, fleet_guard, remote_worker, shipped_weights):
    """The tier-1 remote smoke: a standalone `--listen` worker attached
    by address boots from the SHIPPED sha256-verified weight artifact
    (bit-identical to the weight-seed oracle, which the factory seed
    cannot produce), liveness rides beat frames (no heartbeat file), a
    retried submit after a forced ack loss admits exactly once, and a
    manager re-attach after detach ships zero bytes onto the cached
    engine under a fresh epoch — with the net_delay trickle armed."""
    model, wpath = shipped_weights
    addr, proc = remote_worker(index=0)
    fleet = FleetRouter([], heartbeat_timeout_s=5.0)
    fleet_guard(fleet)
    rid = fleet.add_worker(remote_spec(weights=wpath), address=addr,
                           ack_timeout_s=30.0)
    rep = fleet.manager.get(rid)
    assert isinstance(rep, RemoteReplica) and rep.kind == "remote"
    drive(fleet, lambda: rep.state == "healthy", 120, "remote boot")
    client = rep.engine
    assert client.heartbeat_path is None  # liveness is beat FRAMES
    assert client.epoch == 1 and client.weights_sha is not None
    assert client.bytes_shipped > 0
    assert client.pid > 0 and client.pid == proc.pid
    snap = rep.snapshot()
    assert snap["kind"] == "remote" and snap["address"] == addr
    assert snap["weights_sha"] == client.weights_sha
    assert snap["epoch"] == 1 and snap["bytes_shipped"] > 0
    assert fleet.health()["remote_workers"] == 1
    prompt = np.arange(1, 6, dtype=np.int32)
    want = oracle(model, prompt, 12)
    # mild slowloris on every 5th manager frame: streams still complete
    faults.enable("net_delay", "2:5")
    # -- exactly-once admission under injected ack loss: ship, then
    # force the ack-timeout resend path twice; the worker's wid dedup
    # re-acks without double-admitting, so the stream is bit-identical
    # (a double admission would push duplicate chunks into the run)
    req, resp = client.make_request(prompt, 12, resubmit=False)
    client._ship(req, resp)
    wid = next(iter(client._await_ack))
    for _ in range(2):
        client._await_ack[wid][0] = 0.0  # ack "lost": deadline now
        client._pump_acks()
    assert client._await_ack[wid][1] == client.submit_retries - 2
    drive(fleet, resp.done, 60, "deduped stream completion")
    assert resp.tokens() == want
    assert not client._await_ack
    assert client.post_warmup_compiles() == 0
    drive(fleet, lambda: (client.heartbeat_age() is not None
                          and client.heartbeat_steps() is not None),
          30, "beat frames")
    assert client.heartbeat_age() < 5.0
    faults.disable("net_delay")
    # -- detach: the manager does NOT own the process
    fleet.close()
    time.sleep(0.3)
    assert proc.poll() is None, "standalone worker died on manager close"
    # -- re-attach: cached engine, zero bytes re-shipped, fresh epoch
    fleet2 = FleetRouter([], heartbeat_timeout_s=5.0)
    fleet_guard(fleet2)
    rid2 = fleet2.add_worker(remote_spec(weights=wpath), address=addr)
    rep2 = fleet2.manager.get(rid2)
    drive(fleet2, lambda: rep2.state == "healthy", 60, "re-attach")
    assert rep2.engine.bytes_shipped == 0
    assert rep2.engine.weights_sha == client.weights_sha
    assert rep2.engine.post_warmup_compiles() == 0
    req2, resp2 = rep2.engine.make_request(prompt, 12)
    rep2.engine.scheduler.submit(req2, resp2)
    drive(fleet2, resp2.done, 60, "post-re-attach stream")
    assert resp2.tokens() == want


def test_stale_epoch_reject_and_higher_epoch_takeover(
        hard_timeout, fleet_guard, remote_worker):
    """Split-brain fencing on the worker's listener: an attach with an
    EQUAL epoch is refused with a typed StaleEpochError fatal; a HIGHER
    epoch supersedes the live session — its residents abort typed
    (StaleEpochError reaches the old manager's consumers) and the new
    session serves.  No token is ever double-served."""
    addr, _ = remote_worker(index=0)
    spec = remote_spec()
    prompt = np.arange(1, 6, dtype=np.int32)
    want = oracle(tiny_model(FACTORY_SEED), prompt, 24)
    cl_a = RemoteWorkerClient(spec, addr, index=0, epoch=5,
                              manager_silence_s=30.0)
    fleet_guard(cl_a)
    cl_a.warmup()
    # keep A's stream resident: slow the worker's decode
    cl_a.set_fault("replica_slow", "60:1:0")
    req_a, resp_a = cl_a.make_request(prompt, 24, resubmit=False)
    cl_a._ship(req_a, resp_a)
    drive_client(cl_a, lambda: len(resp_a.tokens_so_far()) >= 1, 60,
                 "stream resident on the remote worker")
    # -- equal epoch: refused typed before any session damage
    cl_stale = RemoteWorkerClient(spec, addr, index=0, epoch=5,
                                  boot_timeout_s=30.0)
    fleet_guard(cl_stale)
    with pytest.raises(WorkerDiedError, match="StaleEpochError"):
        t0 = time.monotonic()
        while True:
            try:
                cl_a.step()  # the worker polls its listener per step
            except (WorkerDiedError, WireFormatError):
                pass
            if cl_stale.poll_ready():
                raise AssertionError("stale epoch was admitted")
            assert time.monotonic() - t0 < 60
            time.sleep(0.01)
    # A's session is untouched by the refused stale attach
    assert resp_a.error is None and not resp_a.done()
    # -- higher epoch: takeover.  A's resident aborts typed; the worker
    # reuses its cached engine for B (same spec, no weights)
    cl_b = RemoteWorkerClient(spec, addr, index=0, epoch=6)
    fleet_guard(cl_b)
    drive_client(cl_a, resp_a.done, 60, "old-epoch resident aborted")
    assert isinstance(resp_a.error, StaleEpochError)
    assert "superseded by attach epoch 6" in str(resp_a.error)
    cl_b.warmup()
    assert cl_b.epoch == 6
    cl_b.set_fault("replica_slow", None)
    req_b, resp_b = cl_b.make_request(prompt, 24)
    cl_b._ship(req_b, resp_b)
    drive_client(cl_b, resp_b.done, 60, "new-epoch stream")
    assert resp_b.tokens() == want


def test_corrupt_weight_chunk_typed_reject_then_supervised_reattach(
        hard_timeout, fleet_guard, remote_worker, shipped_weights,
        monkeypatch):
    """ISSUE-15 satellite: a corrupted weight chunk is refused typed by
    the worker's per-chunk sha256 check (never assembled into garbage
    weights), the boot failure burns one restart-budget attempt, and the
    supervisor's re-attach (epoch+1) ships clean and serves the shipped
    weights bit-identical."""
    import paddle_tpu.serving.transfer as transfer
    model, wpath = shipped_weights
    real_iter = transfer.iter_artifact_chunks
    calls = {"n": 0}

    def corrupting(path, *a, **kw):
        calls["n"] += 1
        poison = calls["n"] == 1
        for seq, data in real_iter(path, *a, **kw):
            if poison and seq == 0:
                data = b"\x00" * len(data)
            yield seq, data

    monkeypatch.setattr(transfer, "iter_artifact_chunks", corrupting)
    addr, _ = remote_worker(index=0)
    fleet = FleetRouter(
        [], heartbeat_timeout_s=5.0,
        restart_backoff=RestartBackoff(max_restarts=1, base_delay=0.05,
                                       max_delay=0.2))
    fleet_guard(fleet)
    rid = fleet.add_worker(remote_spec(weights=wpath), address=addr)
    rep = fleet.manager.get(rid)

    def healthy_remote():
        return next((r for r in fleet.manager.replicas()
                     if isinstance(r, RemoteReplica)
                     and r.state == "healthy"), None)

    drive(fleet, lambda: healthy_remote() is not None, 120,
          "supervised re-attach after the poisoned ship")
    # the first attach died TYPED on the sha mismatch
    assert rep.state == "crashed"
    assert "WeightShipError" in rep.fence_reason
    assert "sha256 mismatch" in rep.fence_reason
    new_rep = healthy_remote()
    assert new_rep.id != rid
    assert new_rep.lineage["restarts"] == 1
    assert new_rep.lineage["epoch"] == 2 and new_rep.engine.epoch == 2
    assert calls["n"] == 2  # clean re-ship, not a cached skip
    assert new_rep.engine.bytes_shipped > 0
    prompt = np.arange(1, 6, dtype=np.int32)
    req, resp = new_rep.engine.make_request(prompt, 12)
    new_rep.engine.scheduler.submit(req, resp)
    drive(fleet, resp.done, 60, "post-retry stream")
    assert resp.tokens() == oracle(model, prompt, 12)
    assert fleet.manager.counters()["worker_restarts"] == 1


def test_remote_wedge_fences_on_beat_age_without_heartbeat_file(
        hard_timeout, fleet_guard, remote_worker):
    """PDTPU_FAULT_REPLICA_WEDGE on a REMOTE worker: no heartbeat file
    exists (heartbeat_path is None) — ONLY the beat-frame arrival age
    fences it, the resubmit opt-in stream fails over bit-identical onto
    the in-process survivor, and the zero-budget lineage is removed."""
    model = tiny_model(FACTORY_SEED)
    fleet = FleetRouter(
        [ServingEngine(model, **ENGINE_KW)],
        heartbeat_timeout_s=0.8, kill_grace_s=0.2,
        restart_backoff=RestartBackoff(max_restarts=0))
    fleet_guard(fleet)
    # in-process survivor took replica id 0; align the worker's fault
    # index with the lineage index the fleet will assign (1)
    addr, proc = remote_worker(index=1)
    rid = fleet.add_worker(remote_spec(), address=addr)
    rep = fleet.manager.get(rid)
    assert rep.lineage["index"] == 1
    fleet.warmup()
    fleet.start()
    wait_for(lambda: rep.state == "healthy", 120, "remote boot")
    assert rep.engine.heartbeat_path is None
    prompt = np.arange(1, 6, dtype=np.int32)
    want = oracle(model, prompt, 24)
    rep.engine.set_fault("replica_slow", "60:1:1")
    req, resp = rep.engine.make_request(prompt, 24, resubmit=True)
    rep.engine.scheduler.submit(req, resp)
    wait_for(lambda: len(resp.tokens_so_far()) >= 1, 60,
             "stream resident on the remote worker")
    rep.engine.set_fault("replica_wedge", "1:0")
    t_arm = time.monotonic()
    # beat frames stop; the fence is driven purely by their arrival age
    assert resp.tokens(timeout=60) == want
    detect_s = time.monotonic() - t_arm
    assert rep.state == "wedged"
    assert "heartbeat age" in rep.fence_reason
    assert detect_s < 5.0
    # zero budget: lineage exhausted, replica removed — and the manager
    # does NOT kill a process it never owned
    wait_for(lambda: fleet.manager.get(rid) is None, 30,
             "exhausted remote lineage removed")
    assert rep.lineage["exhausted"]
    c = fleet.manager.counters()
    assert c["wedges"] == 1 and c["worker_restarts"] == 0
    assert proc.poll() is None  # wedged REMOTE process is not ours to kill


# ---------------------------------------------------------------------------
# chaos matrix (slow): mid-frame cuts and hard partitions
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_net_drop_midframe_typed_failover_and_reattach(
        hard_timeout, fleet_guard, remote_worker, shipped_weights):
    """PDTPU_FAULT_NET_DROP on the manager side: a frame cut mid-send
    kills the session typed — the resubmit opt-in streams complete
    bit-identical on the in-process survivor and the supervisor
    re-attaches the SAME standalone worker (epoch+1), which serves
    again."""
    model, wpath = shipped_weights
    fleet = FleetRouter(
        [ServingEngine(tiny_model(WEIGHT_SEED), **ENGINE_KW)],
        heartbeat_timeout_s=5.0,
        restart_backoff=RestartBackoff(max_restarts=2, base_delay=0.05,
                                       max_delay=0.2))
    fleet_guard(fleet)
    addr, proc = remote_worker(index=1)
    rid = fleet.add_worker(remote_spec(weights=wpath), address=addr)
    rep = fleet.manager.get(rid)
    fleet.warmup()
    fleet.start()
    wait_for(lambda: rep.state == "healthy", 120, "remote boot")
    prompt = np.arange(1, 6, dtype=np.int32)
    want = oracle(model, prompt, 24)
    rep.engine.set_fault("replica_slow", "60:1:1")
    r1, resp1 = rep.engine.make_request(prompt, 24, resubmit=True)
    rep.engine.scheduler.submit(r1, resp1)
    wait_for(lambda: len(resp1.tokens_so_far()) >= 1, 60,
             "stream resident on the remote worker")
    # the very next manager frame is cut mid-send: the submit below
    faults.enable("net_drop", "1")
    r2, resp2 = rep.engine.make_request(prompt, 24, resubmit=True)
    rep.engine.scheduler.submit(r2, resp2)
    # both streams fail over to the survivor, bit-identical
    assert resp1.tokens(timeout=90) == want
    assert resp2.tokens(timeout=90) == want
    faults.disable("net_drop")
    # the worker survived its manager's torn stream and re-attaches
    wait_for(lambda: any(isinstance(r, RemoteReplica)
                         and r.state == "healthy"
                         for r in fleet.manager.replicas()), 120,
             "supervised re-attach after the mid-frame cut")
    new_rep = next(r for r in fleet.manager.replicas()
                   if isinstance(r, RemoteReplica)
                   and r.state == "healthy")
    assert new_rep.lineage["epoch"] >= 2
    assert proc.poll() is None
    new_rep.engine.set_fault("replica_slow", None)
    r3, resp3 = new_rep.engine.make_request(prompt, 24)
    new_rep.engine.scheduler.submit(r3, resp3)
    assert resp3.tokens(timeout=90) == want
    assert fleet.manager.counters()["worker_restarts"] >= 1


@pytest.mark.slow
def test_net_partition_fences_self_aborts_and_heals(
        hard_timeout, fleet_guard, remote_worker, shipped_weights):
    """PDTPU_FAULT_NET_PARTITION: both directions blackholed with every
    process alive.  The manager fences on beat-frame age within 2x the
    threshold and resubmits onto the survivor (bit-identical); the
    isolated worker self-aborts its residents after manager-silence and
    returns to listening; after the window heals, the supervisor's
    re-attach under a HIGHER epoch is accepted and serves — zero
    double-served tokens, zero hung consumers."""
    model, wpath = shipped_weights
    hb_timeout = 0.8
    fleet = FleetRouter(
        [ServingEngine(tiny_model(WEIGHT_SEED), **ENGINE_KW)],
        heartbeat_timeout_s=hb_timeout, kill_grace_s=0.2,
        # first re-attach lands AFTER the 2.5s partition window heals: a
        # mid-partition attach would just time out and burn budget
        restart_backoff=RestartBackoff(max_restarts=3, base_delay=2.0,
                                       max_delay=3.0))
    fleet_guard(fleet)
    addr, proc = remote_worker(index=1)
    rid = fleet.add_worker(remote_spec(weights=wpath), address=addr,
                           boot_timeout_s=8.0, manager_silence_s=1.5)
    rep = fleet.manager.get(rid)
    fleet.warmup()
    fleet.start()
    wait_for(lambda: rep.state == "healthy", 120, "remote boot")
    prompt = np.arange(1, 6, dtype=np.int32)
    want = oracle(model, prompt, 24)
    rep.engine.set_fault("replica_slow", "60:1:1")
    req, resp = rep.engine.make_request(prompt, 24, resubmit=True)
    rep.engine.scheduler.submit(req, resp)
    wait_for(lambda: len(resp.tokens_so_far()) >= 1, 60,
             "stream resident on the remote worker")
    # arm the WORKER side first (the RPC frame must still get through),
    # then this side: both directions blackholed, every process alive
    rep.engine.set_fault("net_partition", "1:2.5")
    faults.enable("net_partition", "1:2.5")
    t_arm = time.monotonic()
    # the opted-in stream fails over on beat-arrival age alone
    assert resp.tokens(timeout=90) == want
    detect_s = time.monotonic() - t_arm
    assert rep.state == "wedged"
    assert "heartbeat age" in rep.fence_reason
    assert detect_s < 2 * hb_timeout + 2.0
    assert proc.poll() is None  # partitioned, not dead
    # heal: the supervisor re-attaches under a fresh epoch; the worker
    # (which self-aborted on manager silence and went back to
    # listening) accepts it and serves bit-identical again
    wait_for(lambda: any(isinstance(r, RemoteReplica)
                         and r.state == "healthy"
                         for r in fleet.manager.replicas()), 120,
             "healed re-attach after the partition window")
    new_rep = next(r for r in fleet.manager.replicas()
                   if isinstance(r, RemoteReplica)
                   and r.state == "healthy")
    assert new_rep.lineage["epoch"] >= 2
    assert new_rep.engine.epoch == new_rep.lineage["epoch"]
    new_rep.engine.set_fault("replica_slow", None)
    r2, resp2 = new_rep.engine.make_request(prompt, 24)
    new_rep.engine.scheduler.submit(r2, resp2)
    assert resp2.tokens(timeout=90) == want
    c = fleet.manager.counters()
    assert c["wedges"] >= 1 and c["worker_restarts"] >= 1
    assert c["resubmits"] >= 1
