"""Model zoo smoke + training tests (reference analogue: tests/book/ models
and dist_transformer.py — small configs trained a few steps, loss decreases)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import models


TINY = dict(vocab_size=128, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=64,
            max_position_embeddings=64)


def _tokens(b, s, vocab=128):
    return paddle.to_tensor(
        np.random.randint(0, vocab, (b, s)).astype("int32"))


def test_bert_forward_shapes():
    model = models.BertForPretraining(models.BertConfig(**TINY))
    model.eval()
    ids = _tokens(2, 16)
    logits, nsp = model(ids)
    assert logits.shape == [2, 16, 128]
    assert nsp.shape == [2, 2]


def test_bert_attention_mask():
    model = models.BertModel(models.BertConfig(**TINY))
    model.eval()
    ids = _tokens(2, 8)
    mask = paddle.to_tensor(np.array([[1] * 8, [1] * 4 + [0] * 4], "int32"))
    seq, pooled = model(ids, attention_mask=mask)
    assert seq.shape == [2, 8, 32]


def test_bert_train_step_loss_decreases():
    model = models.BertForPretraining(models.BertConfig(**TINY))
    crit = models.BertPretrainingCriterion()
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    ids = _tokens(4, 16)
    labels = _tokens(4, 16)
    nsp_labels = paddle.to_tensor(np.random.randint(0, 2, (4,)).astype("int64"))
    losses = []
    for _ in range(5):
        logits, nsp = model(ids)
        loss = crit(logits, nsp, labels, nsp_labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_gpt_forward_and_train():
    cfg = models.GPTConfig(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                           num_attention_heads=4, max_position_embeddings=64)
    model = models.GPTForPretraining(cfg)
    crit = models.GPTPretrainingCriterion()
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    ids = _tokens(2, 16)
    labels = _tokens(2, 16)
    losses = []
    for _ in range(5):
        logits = model(ids)
        loss = crit(logits, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert logits.shape == [2, 16, 128]
    assert losses[-1] < losses[0]


def test_gpt_causal():
    """Causal property: logits at position t don't depend on tokens > t."""
    cfg = models.GPTConfig(vocab_size=64, hidden_size=32, num_hidden_layers=2,
                           num_attention_heads=4, max_position_embeddings=32,
                           hidden_dropout_prob=0.0,
                           attention_probs_dropout_prob=0.0)
    model = models.GPTForPretraining(cfg)
    model.eval()
    a = np.random.randint(0, 64, (1, 8)).astype("int32")
    b = a.copy()
    b[0, -1] = (b[0, -1] + 1) % 64
    la = model(paddle.to_tensor(a)).numpy()
    lb = model(paddle.to_tensor(b)).numpy()
    np.testing.assert_allclose(la[0, :-1], lb[0, :-1], rtol=2e-4, atol=2e-4)
    assert not np.allclose(la[0, -1], lb[0, -1])


def test_gpt_kv_cache_decode_matches_full():
    cfg = models.GPTConfig(vocab_size=64, hidden_size=32, num_hidden_layers=2,
                           num_attention_heads=4, max_position_embeddings=32,
                           hidden_dropout_prob=0.0,
                           attention_probs_dropout_prob=0.0)
    model = models.GPTForPretraining(cfg)
    model.eval()
    ids = np.random.randint(0, 64, (1, 6)).astype("int32")
    full = model(paddle.to_tensor(ids)).numpy()
    cache = model.gpt.gen_cache(batch_size=1)
    outs = []
    for t in range(6):
        logits, cache = model(paddle.to_tensor(ids[:, t:t + 1]), cache=cache)
        outs.append(logits.numpy()[:, 0])
    inc = np.stack(outs, axis=1)
    np.testing.assert_allclose(full, inc, rtol=2e-3, atol=2e-3)


def test_ernie_forward_and_configs():
    cfg = models.ErnieConfig(vocab_size=128, hidden_size=32,
                             num_hidden_layers=2, num_attention_heads=4,
                             intermediate_size=64, max_position_embeddings=64)
    model = models.ErnieForPretraining(cfg)
    model.eval()
    ids = _tokens(2, 8)
    logits, nsp = model(ids)
    assert logits.shape == [2, 8, 128]
    large = models.ernie_large_config()
    assert large.hidden_size == 1024 and large.num_hidden_layers == 24


def test_bert_large_config():
    c = models.bert_large_config()
    assert (c.hidden_size, c.num_hidden_layers, c.num_attention_heads,
            c.intermediate_size) == (1024, 24, 16, 4096)


def test_bert_state_dict_roundtrip(tmp_path):
    model = models.BertModel(models.BertConfig(**TINY))
    path = str(tmp_path / "bert.pdparams")
    paddle.save(model.state_dict(), path)
    model2 = models.BertModel(models.BertConfig(**TINY))
    model2.set_state_dict(paddle.load(path))
    model.eval(); model2.eval()
    ids = _tokens(2, 8)
    np.testing.assert_allclose(model(ids)[0].numpy(), model2(ids)[0].numpy(),
                               rtol=1e-5, atol=1e-5)


def test_gpt_chunked_decode_matches_full():
    """Chunked prefill with kv-cache must stay causal (regression: multi-token
    chunks with a non-empty cache previously attended to future tokens)."""
    cfg = models.GPTConfig(vocab_size=64, hidden_size=32, num_hidden_layers=2,
                           num_attention_heads=4, max_position_embeddings=32,
                           hidden_dropout_prob=0.0,
                           attention_probs_dropout_prob=0.0)
    model = models.GPTForPretraining(cfg)
    model.eval()
    ids = np.random.randint(0, 64, (1, 8)).astype("int32")
    full = model(paddle.to_tensor(ids)).numpy()
    cache = model.gpt.gen_cache(batch_size=1)
    l1, cache = model(paddle.to_tensor(ids[:, :4]), cache=cache)
    l2, cache = model(paddle.to_tensor(ids[:, 4:]), cache=cache)
    chunked = np.concatenate([l1.numpy(), l2.numpy()], axis=1)
    np.testing.assert_allclose(full, chunked, rtol=2e-3, atol=2e-3)


def test_adamw_apply_decay_param_fun():
    """Params excluded by apply_decay_param_fun must not be decayed."""
    a = paddle.nn.Linear(4, 4)
    for name, p in a.named_parameters():
        p.name = name
    opt = paddle.optimizer.AdamW(
        learning_rate=0.0,  # zero lr: only decay could move params
        weight_decay=0.5,
        parameters=a.parameters(),
        apply_decay_param_fun=lambda n: "bias" not in n)
    before = {n: p.numpy().copy() for n, p in a.named_parameters()}
    out = a(paddle.to_tensor(np.ones((2, 4), "float32")))
    out.sum().backward()
    opt.step()
    # lr=0 -> adam update is 0 and decay term (lr*wd*p) is also 0; use lr>0
    opt2 = paddle.optimizer.AdamW(
        learning_rate=0.1, weight_decay=0.5, beta1=0.0, beta2=0.0,
        parameters=a.parameters(),
        apply_decay_param_fun=lambda n: "bias" not in n)
    zero_grads = True
    for n, p in a.named_parameters():
        p.grad = paddle.to_tensor(np.zeros(p.shape, "float32"))
    opt2.step()
    after = {n: p.numpy() for n, p in a.named_parameters()}
    # bias: no decay, zero grad -> unchanged; weight: decayed
    np.testing.assert_allclose(after["bias"], before["bias"], atol=1e-6)
    assert not np.allclose(after["weight"], before["weight"])


def test_optimizer_changing_param_set():
    """Optimizer must rebuild its jitted update when the set of grad-bearing
    params changes between steps (regression: stale closure skipped params)."""
    a = paddle.nn.Linear(3, 3)
    b = paddle.nn.Linear(3, 3)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=a.parameters() + b.parameters())
    x = paddle.to_tensor(np.ones((2, 3), "float32"))
    # step 1: only `a` has grads
    a(x).sum().backward()
    opt.step(); opt.clear_grad()
    b_before = b.weight.numpy().copy()
    # step 2: both have grads
    (a(x).sum() + b(x).sum()).backward()
    opt.step()
    assert not np.allclose(b.weight.numpy(), b_before)


def test_run_steps_matches_per_call_steps():
    """K steps in one compiled call == K separate step() calls."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import models
    from paddle_tpu.jit import TrainStep

    def make():
        paddle.seed(0)
        cfg = models.BertConfig(vocab_size=64, hidden_size=32,
                                num_hidden_layers=2, num_attention_heads=4,
                                intermediate_size=64,
                                max_position_embeddings=16,
                                hidden_dropout_prob=0.0,
                                attention_probs_dropout_prob=0.0)
        model = models.BertForPretraining(cfg)
        crit = models.BertPretrainingCriterion()
        opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())
        return model, TrainStep(model, lambda l, n, y: crit(l, n, y), opt)

    rng = np.random.RandomState(0)
    stack_ids = rng.randint(0, 64, (3, 4, 16)).astype("int32")
    stack_lbl = rng.randint(0, 64, (3, 4, 16)).astype("int32")

    m1, s1 = make()
    per_call = [float(s1(paddle.to_tensor(stack_ids[i]),
                         paddle.to_tensor(stack_lbl[i]))) for i in range(3)]

    m2, s2 = make()
    multi = s2.run_steps(paddle.to_tensor(stack_ids),
                         paddle.to_tensor(stack_lbl))
    multi = [float(x) for x in np.asarray(multi.numpy())]
    # identical data + zero dropout -> identical loss trajectories
    np.testing.assert_allclose(multi, per_call, rtol=1e-5, atol=1e-6)
    for k, v in m1.state_dict().items():
        np.testing.assert_allclose(np.asarray(v.numpy()),
                                   np.asarray(m2.state_dict()[k].numpy()),
                                   rtol=1e-4, atol=1e-5, err_msg=k)
