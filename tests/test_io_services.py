"""Filesystem clients + AES crypto (reference framework/io/fs.cc,
framework/io/crypto/, fleet/utils/fs.py)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io.fs import (LocalFS, HDFSClient, get_fs, ExecuteError,
                              FSFileExistsError, FSFileNotExistsError)


class TestLocalFS:
    def test_roundtrip(self, tmp_path):
        fs = LocalFS()
        root = str(tmp_path / "a")
        fs.mkdirs(root)
        assert fs.is_dir(root) and fs.is_exist(root)
        f = os.path.join(root, "x.txt")
        fs.touch(f)
        assert fs.is_file(f)
        fs.touch(f, exist_ok=True)
        with pytest.raises(FSFileExistsError):
            fs.touch(f, exist_ok=False)
        fs.mkdirs(os.path.join(root, "sub"))
        dirs, files = fs.ls_dir(root)
        assert dirs == ["sub"] and files == ["x.txt"]
        assert fs.list_dirs(root) == ["sub"]
        assert not fs.need_upload_download()

    def test_mv_delete(self, tmp_path):
        fs = LocalFS()
        src = str(tmp_path / "src.bin")
        dst = str(tmp_path / "dst.bin")
        with open(src, "wb") as f:
            f.write(b"hello")
        fs.mv(src, dst)
        assert not fs.is_exist(src) and fs.is_file(dst)
        with pytest.raises(FSFileNotExistsError):
            fs.mv(str(tmp_path / "nope"), dst, test_exists=True)
        open(src, "wb").close()
        with pytest.raises(FSFileExistsError):
            fs.mv(src, dst, overwrite=False, test_exists=True)
        fs.mv(src, dst, overwrite=True)
        fs.delete(dst)
        assert not fs.is_exist(dst)
        fs.delete(dst)  # idempotent

    def test_get_fs_scheme(self):
        assert isinstance(get_fs("/tmp/x"), LocalFS)
        if os.path.exists("/usr/bin/hadoop"):
            assert isinstance(get_fs("hdfs://x"), HDFSClient)
        else:
            with pytest.raises(ExecuteError):
                get_fs("hdfs://x")

    def test_hdfs_gated(self):
        with pytest.raises(ExecuteError):
            HDFSClient(hadoop_home="/nonexistent-hadoop")


class TestFleetUtils:
    def test_utilbase(self, tmp_path):
        from paddle_tpu.distributed.fleet.utils import UtilBase
        u = UtilBase()
        files = [f"f{i}" for i in range(10)]
        shard = u.get_file_shard(files)
        assert set(shard) <= set(files) and shard
        out = u.all_reduce(np.arange(4.0), mode="sum")
        np.testing.assert_allclose(out, np.arange(4.0))  # world of one
        u.barrier()


native_crypto = pytest.importorskip("paddle_tpu.io.crypto")
if not native_crypto.available():  # pragma: no cover - g++ always in image
    pytest.skip("native crypto unavailable", allow_module_level=True)


class TestCrypto:
    def test_fips197_known_answer(self):
        # FIPS-197 appendix C.1: AES-128
        key = bytes(range(16))
        pt = bytes.fromhex("00112233445566778899aabbccddeeff")
        ct = native_crypto.encrypt_block(key, pt)
        assert ct.hex() == "69c4e0d86a7b0430d8cdb78070b4c55a"
        # appendix C.3: AES-256
        key256 = bytes(range(32))
        ct256 = native_crypto.encrypt_block(key256, pt)
        assert ct256.hex() == "8ea2b7ca516745bfeafc49904b496089"

    def test_roundtrip(self):
        from paddle_tpu.io.crypto import AESCipher, CipherUtils
        c = AESCipher()
        key = CipherUtils.gen_key(128)
        msg = os.urandom(1000) + b"tail"  # non-multiple of block size
        enc = c.encrypt(msg, key)
        # header (magic 4 + version 1 + IV 16) + 32-byte HMAC tag (v2)
        assert enc != msg and len(enc) == len(msg) + 53
        assert c.decrypt(enc, key) == msg
        # v2 is authenticated: a wrong key fails closed instead of
        # yielding attacker-decodable garbage (advisor r2 hardening)
        wrong = CipherUtils.gen_key(128)
        with pytest.raises(ValueError, match="integrity"):
            c.decrypt(enc, wrong)
        # tampering any ciphertext byte is rejected
        bad = bytearray(enc); bad[30] ^= 1
        with pytest.raises(ValueError, match="integrity"):
            c.decrypt(bytes(bad), key)

    def test_v1_downgrade_rejected(self):
        # advisor r3: rewriting the version byte to 1 and stripping the tag
        # must not silently bypass the v2 HMAC
        from paddle_tpu.io.crypto import AESCipher, CipherUtils
        c = AESCipher()
        key = CipherUtils.gen_key(128)
        msg = b"downgrade-me" * 10
        enc = bytearray(c.encrypt(msg, key))
        enc[4] = 1                      # version byte
        v1 = bytes(enc[:-32])           # strip HMAC tag
        with pytest.raises(ValueError, match="downgrade|legacy|v1"):
            c.decrypt(v1, key)
        # explicit opt-in still reads trusted legacy files (CTR unchanged)
        assert c.decrypt(v1, key, allow_legacy=True) == msg

    def test_file_roundtrip(self, tmp_path):
        from paddle_tpu.io.crypto import AESCipher, CipherUtils
        c = AESCipher()
        key = CipherUtils.gen_key_to_file(256, str(tmp_path / "k"))
        assert CipherUtils.read_key_from_file(str(tmp_path / "k")) == key
        c.encrypt_to_file(b"secret-weights", key, str(tmp_path / "m.enc"))
        assert c.decrypt_from_file(key, str(tmp_path / "m.enc")) \
            == b"secret-weights"
        with pytest.raises(ValueError):
            c.decrypt(b"garbage-not-encrypted-data", key)

    def test_encrypted_save_load(self, tmp_path):
        from paddle_tpu.io.crypto import CipherUtils
        key = CipherUtils.gen_key(128)
        state = {"w": paddle.to_tensor(np.arange(6.0).reshape(2, 3))}
        p = str(tmp_path / "model.pdparams.enc")
        paddle.save(state, p, encrypt_key=key)
        # on-disk bytes must not be a plain pickle
        with open(p, "rb") as f:
            raw = f.read()
        assert raw[:4] == b"PDTC"
        back = paddle.load(p, encrypt_key=key)
        np.testing.assert_allclose(np.asarray(back["w"].numpy()),
                                   np.arange(6.0).reshape(2, 3))
