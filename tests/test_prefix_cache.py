"""Prefix-aware KV reuse (ISSUE-17): radix cache over paged blocks,
copy-on-write, and prefix-affine fleet routing.

Covers: the radix index units (full-block matching, share-key
partitioning, LRU eviction over refcount-0 leaves with child-before-
parent drain, first-wins duplicate insertion), the allocator's refcount
lifecycle (adopt/free/shared accounting, cached-counts-as-free
admission, reclaim-under-pressure, cow_last), warm-hit stream
bit-identity (greedy AND sampled, mixed warm/cold traffic, compile
bound unchanged at len(buckets)+1 with ZERO post-warmup compiles),
COW parity + counters, tenant isolation of CACHED blocks (a block
cached by tenant A is never mapped into tenant B's table without an
explicit share group), the PDTPU_FAULT_PREFIX_EVICT live cap, paged
preempt/restore re-pinning, and the fleet router's prefix-hash affinity
(bounded LRU shared with session affinity, re-homing on drain)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import models
from paddle_tpu.serving import (FleetRouter, PagedKVPool, PrefixCache,
                                ServingEngine)
from paddle_tpu.utils import faults

pytestmark = pytest.mark.prefix_cache


def tiny_gpt():
    cfg = models.GPTConfig(vocab_size=13, hidden_size=16,
                           num_hidden_layers=2, num_attention_heads=2,
                           hidden_dropout_prob=0.0,
                           attention_probs_dropout_prob=0.0,
                           max_position_embeddings=64)
    paddle.seed(7)
    m = models.GPTForPretraining(cfg)
    m.eval()
    return m


def solo(model, prompt, max_new, **kw):
    out, _ = model.generate(paddle.to_tensor(
        np.asarray(prompt, np.int32)[None]), max_new_tokens=max_new, **kw)
    return np.asarray(out.numpy())[0].tolist()


def prefix_engine(m, **kw):
    args = dict(max_slots=3, max_len=48, prefill_buckets=(8, 16),
                decode_chunk=4, kv="paged", block_size=8,
                prefix_cache=True)
    args.update(kw)
    return ServingEngine(m, **args)


# ---------------------------------------------------------------------------
# radix index units
# ---------------------------------------------------------------------------

def test_radix_match_insert_and_share_partition():
    pool = PagedKVPool(num_blocks=16, block_size=4, pool_len=32)
    cache = PrefixCache(pool)
    toks = np.arange(12, dtype=np.int32)          # 3 full blocks
    assert pool.alloc(0, rows=12)
    ids = pool.block_ids(0)
    cache.insert("a", toks, ids)
    assert cache.resident_nodes() == 3
    # exact walk, longest-prefix, partial-block tail ignored
    assert cache.match("a", toks) == ids
    assert cache.match("a", toks[:8]) == ids[:2]
    assert cache.match("a", np.concatenate([toks[:8], [99, 98, 97, 96]])
                       ) == ids[:2]
    assert cache.match("a", toks[:6]) == ids[:1]  # 1 full block only
    # divergence in the FIRST block matches nothing
    other = toks.copy()
    other[0] = 9
    assert cache.match("a", other) == []
    # share-key partitioning: tenant b sees NOTHING of tenant a
    assert cache.match("b", toks) == []
    # first-wins: re-inserting the same content under new blocks keeps
    # the original nodes (the duplicate stays slot-private)
    assert pool.alloc(1, rows=12)
    cache.insert("a", toks, pool.block_ids(1))
    assert cache.resident_nodes() == 3
    assert cache.match("a", toks) == ids


def test_lru_eviction_is_leaf_first_and_refcount_aware():
    pool = PagedKVPool(num_blocks=8, block_size=4, pool_len=32)
    cache = PrefixCache(pool)
    toks = np.arange(12, dtype=np.int32)
    assert pool.alloc(0, rows=12)
    ids = pool.block_ids(0)
    cache.insert("t", toks, ids)
    # while slot 0 still references the chain nothing is evictable
    assert cache.evict(3) == []
    pool.free(0)
    assert pool.cached_blocks() == 3 and pool.used_blocks() == 0
    # chains drain child-before-parent: deepest leaf goes first
    freed = cache.evict(1)
    assert freed == [ids[2]]
    assert cache.match("t", toks) == ids[:2]
    # a re-adopted chain pins its blocks against eviction again
    assert pool.adopt(1, cache.match("t", toks))
    assert cache.evict(2) == []
    pool.free(1)
    assert len(cache.evict(2)) == 2
    assert cache.resident_nodes() == 0
    assert pool.free_blocks() == 8


def test_refcount_lifecycle_and_cached_counts_as_free():
    pool = PagedKVPool(num_blocks=8, block_size=4, pool_len=32)
    cache = PrefixCache(pool)
    toks = np.arange(8, dtype=np.int32)
    assert pool.alloc(0, rows=8)
    ids = pool.block_ids(0)
    cache.insert("t", toks, ids)
    # adopt shares the SAME device blocks: refcount 2, live unchanged
    assert pool.adopt(1, ids)
    assert pool.block_ref(ids[0]) == 2
    assert pool.used_blocks() == 2
    assert pool.stats()["shared_blocks"] == 2
    # one holder frees: blocks stay resident (cached), ref drops to 1
    pool.free(0)
    assert pool.block_ref(ids[0]) == 1 and pool.used_blocks() == 2
    pool.free(1)
    # cached refcount-0 blocks count as FREE for admission...
    assert pool.used_blocks() == 0
    assert pool.free_blocks() == 8
    assert pool.cached_blocks() == 2
    # ...and allocation pressure reclaims them through the cache hook
    assert pool.alloc(2, rows=32)          # needs all 8 blocks
    assert pool.cached_blocks() == 0 and cache.resident_nodes() == 0
    assert cache.evictions == 2


def test_cow_last_gives_private_copy():
    pool = PagedKVPool(num_blocks=4, block_size=4, pool_len=16)
    cache = PrefixCache(pool)
    toks = np.arange(8, dtype=np.int32)
    assert pool.alloc(0, rows=8)
    ids = pool.block_ids(0)
    cache.insert("t", toks, ids)
    assert pool.adopt(1, ids)
    src_dst = pool.cow_last(1)
    assert src_dst is not None
    src, dst = src_dst
    assert src == ids[1] and dst not in ids
    assert pool.block_ids(1) == [ids[0], dst]
    # the shared source lost one reference but stays cache-resident
    assert pool.block_ref(src) == 1 and src in pool._cached
    pool.free(1)
    pool.free(0)
    assert pool.used_blocks() == 0
    assert pool.cached_blocks() == 2


# ---------------------------------------------------------------------------
# engine: warm-hit bit-identity, COW parity, zero post-warmup compiles
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def warm_setup():
    m = tiny_gpt()
    eng = prefix_engine(m)
    eng.warmup()
    cold = ServingEngine(m, max_slots=3, max_len=48,
                         prefill_buckets=(8, 16), decode_chunk=4,
                         kv="paged", block_size=8)
    cold.warmup()
    return m, eng, cold


def test_warm_streams_bit_identical_zero_post_warmup_compiles(warm_setup):
    """Mixed warm/cold greedy+sampled traffic: every stream bit-identical
    to its oracle, cache hits actually happen, and NOTHING compiles
    after warmup — engine counters and the program registry agree."""
    from paddle_tpu import observability
    from paddle_tpu.core import op as core_op
    m, eng, cold = warm_setup
    reg = observability.get_program_registry()

    def serving_compiles():
        return {k: v["compiles"] for k, v in reg.snapshot().items()
                if k.startswith("serving_")}

    before = (eng.compile_counts(), serving_compiles(),
              core_op.dispatch_cache_stats()["misses"])
    rng = np.random.RandomState(4)
    template = rng.randint(0, 13, (16,))
    # cold leg populates the cache
    r0 = eng.submit(template.copy(), max_new_tokens=6)
    eng.run_until_drained(timeout=240)
    assert r0.tokens() == solo(m, template, 6)
    hits0 = eng.prefix_cache.hits
    # warm leg: shared template + divergent suffixes, mixed greedy and
    # sampled, interleaved with a cold (uncached) prompt
    warm_prompts = [np.concatenate([template[:8], rng.randint(0, 13, (n,))])
                    for n in (3, 5, 7)]
    cold_prompt = rng.randint(0, 13, (11,))
    greedy = [eng.submit(p, max_new_tokens=6) for p in warm_prompts]
    outsider = eng.submit(cold_prompt, max_new_tokens=6)
    kw = dict(max_new_tokens=5, decode_strategy="sampling",
              temperature=0.8, top_k=4, seed=11)
    sampled = eng.submit(warm_prompts[0], **kw)
    eng.run_until_drained(timeout=240)
    for p, r in zip(warm_prompts, greedy):
        assert r.tokens(timeout=5) == solo(m, p, 6)
    assert outsider.tokens(timeout=5) == solo(m, cold_prompt, 6)
    # sampled warm parity: the no-cache paged engine is the oracle
    oracle = cold.submit(warm_prompts[0], **kw)
    cold.run_until_drained(timeout=240)
    assert sampled.tokens(timeout=5) == oracle.tokens(timeout=5)
    assert eng.prefix_cache.hits > hits0, "warm legs must hit the cache"
    after = (eng.compile_counts(), serving_compiles(),
             core_op.dispatch_cache_stats()["misses"])
    assert after == before, "warm/cold mix must never compile post-warmup"
    cc = eng.compile_counts()
    assert cc["total"] <= cc["bound"] == len(eng.buckets) + 1
    assert eng.kv_pool.used_blocks() == 0


def test_fully_cached_prompt_takes_cow_path(warm_setup):
    m, eng, _ = warm_setup
    rng = np.random.RandomState(9)
    p = rng.randint(0, 13, (16,))          # block-aligned: full-block COW
    want = solo(m, p, 6)
    r1 = eng.submit(p, max_new_tokens=6)
    eng.run_until_drained(timeout=240)
    assert r1.tokens() == want
    cows = eng.prefix_cache.cow_copies
    r2 = eng.submit(p, max_new_tokens=6)
    eng.run_until_drained(timeout=240)
    assert r2.tokens() == want
    assert eng.prefix_cache.cow_copies == cows + 1
    assert eng.kv_pool.used_blocks() == 0
    stats = eng.metrics()["kv_pool"]["prefix_cache"]
    assert stats["cow_copies"] == eng.prefix_cache.cow_copies
    assert stats["hit_rate"] > 0


def test_preempt_restore_repins_prefix(warm_setup):
    """A preempted warm run restores bit-identically: the shared prefix
    is re-adopted from the local cache (not re-uploaded) and nothing
    double-frees at drain."""
    m, eng, _ = warm_setup
    rng = np.random.RandomState(13)
    template = rng.randint(0, 13, (16,))
    warm = eng.submit(template.copy(), max_new_tokens=1)
    eng.run_until_drained(timeout=240)
    p = np.concatenate([template[:8], rng.randint(0, 13, (4,))])
    want = solo(m, p, 8)
    r = eng.submit(p, max_new_tokens=8)
    for _ in range(20):
        eng.step()
        if eng._slots:
            break
    slot = next(iter(eng._slots))
    paused = eng.preempt_slot(slot)
    assert eng.kv_pool.used_blocks() == 0
    assert eng.restore_run(paused)
    eng.run_until_drained(timeout=240)
    assert warm.done() and r.tokens() == want
    assert eng.kv_pool.used_blocks() == 0


# ---------------------------------------------------------------------------
# tenant isolation + share policy
# ---------------------------------------------------------------------------

def test_tenant_isolation_and_share_groups(warm_setup):
    """A block cached by tenant A is NEVER mapped into tenant B's table
    without an explicit share group; with one, B reuses A's blocks.
    Runs on the shared module engine: tenant partitions are independent
    of whatever the default share key already cached."""
    m, eng, _ = warm_setup
    rng = np.random.RandomState(21)
    p = rng.randint(0, 13, (16,))
    want = solo(m, p, 4)
    ra = eng.submit(p.copy(), max_new_tokens=4, tenant="alice")
    eng.run_until_drained(timeout=240)
    assert ra.tokens() == want
    a_chain = eng.prefix_cache.match("alice", p)
    assert len(a_chain) == 2
    # tenant B: same prompt, zero hits, disjoint blocks
    hits = eng.prefix_cache.hits
    rb = eng.submit(p.copy(), max_new_tokens=4, tenant="bob")
    eng.run_until_drained(timeout=240)
    assert rb.tokens() == want
    assert eng.prefix_cache.hits == hits, "cross-tenant hit is a leak"
    b_chain = eng.prefix_cache.match("bob", p)
    assert b_chain and set(b_chain).isdisjoint(a_chain)
    # explicit share group: carol and alice pool their cached prefixes
    eng.set_share_groups({"alice": "team", "carol": "team"})
    rc = eng.submit(p.copy(), max_new_tokens=4, tenant="carol")
    eng.run_until_drained(timeout=240)
    assert rc.tokens() == want
    # alice's blocks moved under the "team" key only going FORWARD; the
    # pre-group blocks stay under "alice" — carol prefilled cold into
    # the team partition and future alice traffic shares it
    hits = eng.prefix_cache.hits
    ra2 = eng.submit(p.copy(), max_new_tokens=4, tenant="alice")
    eng.run_until_drained(timeout=240)
    assert ra2.tokens() == want
    assert eng.prefix_cache.hits > hits, "share group must enable reuse"
    assert eng.kv_pool.used_blocks() == 0


# ---------------------------------------------------------------------------
# fault knob: PDTPU_FAULT_PREFIX_EVICT
# ---------------------------------------------------------------------------

@pytest.mark.faults
def test_prefix_evict_fault_cap_is_live(warm_setup):
    # shared module engine: the cap applies to however much the earlier
    # tests left resident, which is exactly what a live knob must handle
    m, eng, _ = warm_setup
    rng = np.random.RandomState(31)
    p = rng.randint(0, 13, (16,))
    r = eng.submit(p.copy(), max_new_tokens=4)
    eng.run_until_drained(timeout=240)
    assert r.done() and eng.kv_pool.cached_blocks() >= 2
    faults.enable("prefix_evict", "1")
    try:
        # the cap is consulted LIVE at the next release/insert
        r2 = eng.submit(rng.randint(0, 13, (16,)), max_new_tokens=4)
        eng.run_until_drained(timeout=240)
        assert r2.done()
        assert eng.kv_pool.cached_blocks() <= 1
        faults.enable("prefix_evict", "0")
        r3 = eng.submit(rng.randint(0, 13, (16,)), max_new_tokens=4)
        eng.run_until_drained(timeout=240)
        assert r3.done()
        assert eng.kv_pool.cached_blocks() == 0, "N=0 disables retention"
    finally:
        faults.reset()
    assert eng.kv_pool.used_blocks() == 0
    assert eng.prefix_cache.evictions >= 2


# ---------------------------------------------------------------------------
# fleet: prefix-affine routing
# ---------------------------------------------------------------------------

def test_fleet_prefix_affinity_routes_and_rehomes():
    """Sessionless requests sharing a prompt prefix pin to ONE replica
    (where the cached blocks live); the pin lives in the same bounded
    LRU as session affinity and re-homes when the replica drains."""
    m = tiny_gpt()
    # single prefill bucket: the routing claim needs two replicas, not
    # two program families — keep tier-1 compile time down
    engines = [prefix_engine(m, max_slots=2, prefill_buckets=(16,))
               for _ in range(2)]
    fleet = FleetRouter(engines, prefix_affinity=True,
                        prefix_affinity_tokens=8)
    fleet.warmup()
    try:
        rng = np.random.RandomState(41)
        template = rng.randint(0, 13, (16,))
        homes = set()
        for i in range(4):
            p = np.concatenate([template[:8], rng.randint(0, 13, (5,))])
            r = fleet.submit(p, 4)
            fleet.run_until_drained(timeout=240)
            assert r.done()
            key = [k for k in fleet._affinity if k.startswith("px:")]
            assert len(key) == 1, "one prefix, one affinity entry"
            homes.add(fleet._affinity[key[0]])
        assert len(homes) == 1, "same prefix must pin to one replica"
        home = homes.pop()
        # an explicit session still wins over the prefix hash
        rs = fleet.submit(template.copy(), 4, session="u1")
        fleet.run_until_drained(timeout=240)
        assert rs.done() and "u1" in fleet._affinity
        # fence the affine replica: pins re-home, traffic keeps flowing
        fleet.drain(home)
        fleet.run_until_drained(timeout=240)
        assert all(rid != home for rid in fleet._affinity.values())
        r = fleet.submit(np.concatenate([template[:8], [1, 2, 3]]), 4)
        fleet.run_until_drained(timeout=240)
        assert r.done()
        assert fleet.metrics()["prefix_affinity"] is True
    finally:
        fleet.close()


def test_prefix_cache_requires_paged_and_no_spec():
    from paddle_tpu.core.errors import InvalidArgumentError
    m = tiny_gpt()
    with pytest.raises(InvalidArgumentError):
        ServingEngine(m, max_slots=2, max_len=32, prefill_buckets=(8,),
                      prefix_cache=True)   # fixed KV layout
