"""TrainStep(accum_steps=K) — in-program gradient accumulation.

ISSUE-10 regression matrix: K micro-batches scan inside ONE compiled
step with f32 grad accumulators and one optimizer update, so the
accumulated window must match the equivalent full-batch step within f32
accumulation tolerance (exact micro-batch equivalence needs a BN-free
model: BatchNorm normalizes each micro-batch with its own stats by
design — that contract is tested separately), compose with guard=True
finiteness skips and GradScaler skip-and-decay, resume bit-exactly from
an AsyncCheckpointManager checkpoint at a window boundary (rng stream +
cursor + recorded accum_steps), and keep the compile count at one step
program.
"""
import os

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn
from paddle_tpu.jit import TrainStep
from paddle_tpu.utils import faults

pytestmark = pytest.mark.hbm


class _Net(nn.Layer):
    """BN-free conv net: micro-batch gradients average to the full-batch
    gradient exactly (modulo f32 reassociation), so accum windows are
    comparable to full-batch steps at tight tolerance."""

    def __init__(self):
        super().__init__()
        self.conv = nn.Conv2D(3, 8, 3, padding=1)
        self.fc = nn.Linear(8 * 8 * 8, 10)

    def forward(self, x):
        y = F.relu(self.conv(x))
        return self.fc(y.reshape((y.shape[0], -1)))


class _BNNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.conv = nn.Conv2D(3, 8, 3, padding=1)
        self.bn = nn.BatchNorm2D(8)
        self.fc = nn.Linear(8 * 8 * 8, 10)

    def forward(self, x):
        y = self.bn(self.conv(x), activation="relu")
        return self.fc(y.reshape((y.shape[0], -1)))


def _build(K=1, guard=False, cls=_Net, lr=0.1):
    paddle.seed(0)
    model = cls()
    opt = paddle.optimizer.Momentum(learning_rate=lr, momentum=0.9,
                                    parameters=model.parameters())
    step = TrainStep(model, lambda lo, la: F.cross_entropy(lo, la), opt,
                     accum_steps=K, guard=guard)
    return model, step


def _batches(n, b=8, seed=0):
    rng = np.random.RandomState(seed)
    return [(jnp.asarray(rng.randn(b, 3, 8, 8), jnp.float32),
             jnp.asarray(rng.randint(0, 10, (b,)), jnp.int32))
            for _ in range(n)]


def _params(model):
    return {k: np.asarray(v._data).copy()
            for k, v in model.state_dict().items()}


@pytest.mark.parametrize("K", [2, 4])
def test_accum_matches_full_batch_within_f32_tolerance(K):
    batches = _batches(3)
    m_full, s_full = _build(1)
    m_acc, s_acc = _build(K)
    for x, y in batches:
        l_full = float(s_full(x, y))
        l_acc = float(s_acc(x, y))
        # mean of per-micro mean losses == full-batch mean loss
        assert abs(l_full - l_acc) / max(abs(l_full), 1e-12) < 1e-5
    pf, pa = _params(m_full), _params(m_acc)
    for k in pf:
        np.testing.assert_allclose(pa[k], pf[k], rtol=2e-5, atol=2e-6)


def test_accum_one_is_the_plain_step_bit_exact():
    batches = _batches(2)
    m1, s1 = _build(1)
    mk, sk = _build(1)
    for x, y in batches:
        assert float(s1(x, y)) == float(sk(x, y))
    p1, p2 = _params(m1), _params(mk)
    for k in p1:
        np.testing.assert_array_equal(p1[k], p2[k])


def test_accum_bn_stats_compound_per_micro_batch():
    """BatchNorm running stats inside the window update sequentially, one
    micro-batch at a time, exactly like K eager forwards (the scan carries
    the buffer state; trainable params stay at their pre-update values
    for every micro-batch, like the eager oracle)."""
    (x, y), = _batches(1)
    m_acc, s_acc = _build(2, cls=_BNNet)
    s_acc(x, y)

    paddle.seed(0)
    oracle = _BNNet()
    oracle.train()
    for mb in np.split(np.asarray(x), 2):
        oracle(paddle.to_tensor(mb))  # eager forward updates stats

    np.testing.assert_allclose(np.asarray(m_acc.bn._mean._data),
                               np.asarray(oracle.bn._mean._data),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(m_acc.bn._variance._data),
                               np.asarray(oracle.bn._variance._data),
                               rtol=1e-5, atol=1e-6)


def test_accum_guard_skips_poisoned_window():
    model, step = _build(2, guard=True)
    batches = _batches(2)
    # fault presence is baked at trace time: arm before the first compile,
    # targeting the SECOND optimizer step (= second accum window)
    faults.enable("nan_grads", 2)
    try:
        step(*batches[0])
        before = _params(model)
        step(*batches[1])  # poisoned -> on-device skip
    finally:
        faults.reset()
    _, ok = step.last_guard
    assert not bool(np.asarray(ok))
    after = _params(model)
    for k in before:
        np.testing.assert_array_equal(after[k], before[k])


def test_accum_guard_gradscaler_skip_and_decay():
    from paddle_tpu.utils.guarded import GuardedTrainStep
    model, step = _build(2, guard=True)
    scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0,
                                   decr_every_n_nan_or_inf=1)
    gstep = GuardedTrainStep(step, scaler=scaler)
    batches = _batches(2)
    faults.enable("nan_grads", 2)  # armed before trace; fires on window 2
    try:
        gstep(*batches[0])
        assert not gstep.last_skipped
        gstep(*batches[1])
    finally:
        faults.reset()
    assert gstep.last_skipped
    assert scaler.get_init_loss_scaling() < 1024.0  # record_skip decayed


def test_accum_checkpoint_resume_bit_exact(tmp_path):
    """Interrupt after window 3 of 6, restore into a fresh process-alike
    (new model/optimizer/TrainStep), finish — losses and params must be
    bit-identical to the uninterrupted run.  The checkpoint records
    accum_steps so the resumed rng fold_in stream lines up, and the
    async manager publishes durably before the restore."""
    from paddle_tpu.distributed.checkpoint import AsyncCheckpointManager
    from paddle_tpu.jit import state_arrays

    batches = _batches(6, seed=7)
    m0, s0 = _build(4)
    straight = [float(s0(x, y)) for x, y in batches]

    m1, s1 = _build(4)
    part1 = [float(s1(x, y)) for x, y in batches[:3]]
    mgr = AsyncCheckpointManager(str(tmp_path))
    mgr.save_train_state(state_arrays(m1), s1._opt_state,
                         s1.optimizer._step_count,
                         extra_meta={"accum_steps": s1.accum_steps},
                         optimizer=s1.optimizer,
                         data_cursor={"window": 3})
    assert mgr.wait_until_finished(timeout=60.0)
    mgr.close()

    m2, s2 = _build(4)
    meta = s2.restore_checkpoint(str(tmp_path))
    assert meta is not None
    assert meta["accum_steps"] == 4
    assert meta["data_cursor"] == {"window": 3}
    part2 = [float(s2(x, y)) for x, y in batches[3:]]
    assert part1 + part2 == straight
    pa, pb = _params(m0), _params(m2)
    for k in pa:
        np.testing.assert_array_equal(pa[k], pb[k])


def test_accum_compile_count_one_program():
    """The whole K-micro-batch window is ONE compiled step program: more
    windows at the same signature never recompile."""
    from paddle_tpu.observability import get_program_registry
    model, step = _build(4)
    batches = _batches(3)
    step(*batches[0])
    reg = get_program_registry()
    name = f"train_step:{type(model).__name__}"
    rec = reg.get(name)
    compiles = rec["compiles"] if rec else None
    for x, y in batches[1:]:
        step(x, y)
    rec = reg.get(name)
    if rec is not None and compiles is not None:
        assert rec["compiles"] == compiles
    # the compiled-callable identity is stable either way
    assert step._compiled is not None


def test_accum_rejects_bad_configs():
    model, step = _build(2)
    x, y = _batches(1, b=7)[0]  # 7 % 2 != 0
    with pytest.raises(ValueError, match="not divisible"):
        step(x, y)

    with pytest.raises(ValueError, match="with_outputs"):
        paddle.seed(0)
        m = _Net()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=m.parameters())
        TrainStep(m, lambda lo, la: F.cross_entropy(lo, la), opt,
                  accum_steps=2, with_outputs=True)

    with pytest.raises(ValueError, match="accum_steps"):
        paddle.seed(0)
        m = _Net()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=m.parameters())
        TrainStep(m, lambda lo, la: F.cross_entropy(lo, la), opt,
                  accum_steps=0)

    model2, step2 = _build(2)
    stacked = tuple(jnp.stack([b, b]) for b in _batches(1)[0])
    with pytest.raises(NotImplementedError, match="run_steps"):
        step2.run_steps(*stacked)


def test_accum_sparse_embedding_rejected():
    paddle.seed(0)

    class _Emb(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(16, 8, sparse=True)
            self.fc = nn.Linear(8, 4)

        def forward(self, ids):
            return self.fc(self.emb(ids).mean(axis=1))

    m = _Emb()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=m.parameters())
    with pytest.raises(NotImplementedError, match="sparse"):
        TrainStep(m, lambda lo, la: F.cross_entropy(lo, la), opt,
                  accum_steps=2)


def test_sharded_accum_spelling_and_conflict():
    """ShardedTrainStep(accum_steps=K) is the gradient-merge meta-optimizer
    with the TrainStep-shaped name; a disagreeing explicit
    gradient_merge_configs.k_steps is a config error, an agreeing one is
    fine."""
    from paddle_tpu.parallel import ShardedTrainStep
    from paddle_tpu.parallel.strategy import (DistributedStrategy,
                                              GradientMergeConfig)

    paddle.seed(0)
    m = _Net()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=m.parameters())
    st = DistributedStrategy(
        gradient_merge=True,
        gradient_merge_configs=GradientMergeConfig(k_steps=3))
    with pytest.raises(ValueError, match="disagree"):
        ShardedTrainStep(m, lambda lo, la: F.cross_entropy(lo, la), opt,
                         strategy=st, accum_steps=2)
    s = ShardedTrainStep(m, lambda lo, la: F.cross_entropy(lo, la), opt,
                         strategy=st, accum_steps=3)
    assert s.accum_steps == 3
    s2 = ShardedTrainStep(m, lambda lo, la: F.cross_entropy(lo, la), opt,
                          accum_steps=2)
    assert s2.accum_steps == 2
