"""Sharded + automatic checkpointing tests (VERDICT r1 missing #3).

Reference behavior matched: auto-checkpoint resume
(fluid/incubate/checkpoint/auto_checkpoint.py:71) and distributed snapshot
without gathering (PS checkpoint_notify). Kill/resume is simulated by
destroying every Python object and rebuilding from disk; bit-exactness is
asserted against an uninterrupted run.
"""
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import parallel, models
from paddle_tpu.distributed import checkpoint as dck


def _gpt_tiny():
    cfg = models.GPTConfig(vocab_size=64, hidden_size=32, num_hidden_layers=2,
                           num_attention_heads=4, max_position_embeddings=32,
                           hidden_dropout_prob=0.0,
                           attention_probs_dropout_prob=0.0)
    return models.GPTForPretraining(cfg), models.GPTPretrainingCriterion()


def _batches(n, seed=0, b=8, s=16, vocab=64):
    rng = np.random.RandomState(seed)
    return [(rng.randint(0, vocab, (b, s)).astype("int32"),
             rng.randint(0, vocab, (b, s)).astype("int32"))
            for _ in range(n)]


def _fsdp_step():
    model, crit = _gpt_tiny()
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=model.parameters())
    st = parallel.DistributedStrategy(sharding=True)
    st.sharding_configs.stage = 3
    mesh = parallel.create_mesh({"dp": 8})
    step = parallel.ShardedTrainStep(
        model, lambda l, y: crit(l, y), opt, strategy=st, mesh=mesh)
    return step, model


def test_save_restore_roundtrip_sharded(tmp_path):
    mesh = parallel.create_mesh({"dp": 8})
    x = jax.device_put(jnp.arange(64, dtype=jnp.float32).reshape(16, 4),
                       NamedSharding(mesh, P("dp", None)))
    y = jax.device_put(jnp.arange(8, dtype=jnp.int32),
                       NamedSharding(mesh, P()))
    dck.save_sharded({"a": x, "nested": {"b": y}}, str(tmp_path), step=7,
                     extra_meta={"tag": "t"})
    tree, step, extra = dck.restore_sharded(str(tmp_path), mesh=mesh)
    assert step == 7 and extra == {"tag": "t"}
    np.testing.assert_array_equal(np.asarray(tree["a"]), np.asarray(x))
    np.testing.assert_array_equal(np.asarray(tree["nested"]["b"]),
                                  np.asarray(y))
    # restored array keeps the saved sharding
    assert tree["a"].sharding.spec == P("dp", None)


def test_shard_files_hold_shards_not_full_arrays(tmp_path):
    """No host gather: saved npz entries are per-device shards."""
    mesh = parallel.create_mesh({"dp": 8})
    x = jax.device_put(jnp.zeros((16, 4), jnp.float32),
                       NamedSharding(mesh, P("dp", None)))
    dck.save_sharded({"a": x}, str(tmp_path), step=0)
    step_dir = dck.latest_step_dir(str(tmp_path))
    f = np.load(os.path.join(step_dir, "shards-p00000.npz"))
    shard_keys = [k for k in f.files if k.startswith("a@")]
    assert len(shard_keys) == 8
    for k in shard_keys:
        assert f[k].shape == (2, 4)  # 16/8 rows per shard


def test_restore_onto_different_topology(tmp_path):
    """Shards written on dp=8 restore onto a dp=4-shaped layout (the
    reassembly path) and onto plain host arrays (mesh=None)."""
    mesh8 = parallel.create_mesh({"dp": 8})
    x = jax.device_put(jnp.arange(64, dtype=jnp.float32).reshape(16, 4),
                       NamedSharding(mesh8, P("dp", None)))
    dck.save_sharded({"a": x}, str(tmp_path), step=1)

    mesh4 = parallel.create_mesh({"dp": 4, "tp": 2})
    tree, _, _ = dck.restore_sharded(
        str(tmp_path), shardings={"a": NamedSharding(mesh4, P("dp", "tp"))})
    np.testing.assert_array_equal(np.asarray(tree["a"]), np.asarray(x))

    tree, _, _ = dck.restore_sharded(str(tmp_path))
    np.testing.assert_array_equal(np.asarray(tree["a"]), np.asarray(x))


def test_kill_resume_bit_exact(tmp_path):
    """Train 5 steps straight vs train 3 + kill + restore + train 2:
    identical loss trajectory and identical final params."""
    batches = _batches(5, seed=3)

    paddle.seed(42)
    step, model = _fsdp_step()
    straight = [float(step(paddle.to_tensor(i), paddle.to_tensor(l)))
                for i, l in batches]
    final_straight = {k: np.asarray(v._data)
                      for k, v in model.state_dict().items()}

    ckpt = str(tmp_path / "ck")
    paddle.seed(42)
    step1, _ = _fsdp_step()
    part1 = [float(step1(paddle.to_tensor(i), paddle.to_tensor(l)))
             for i, l in batches[:3]]
    step1.save_checkpoint(ckpt, extra_meta={"note": "mid"})
    del step1  # the "kill"

    paddle.seed(999)  # adversarial: resumed run must not depend on init seed
    step2, model2 = _fsdp_step()
    meta = step2.restore_checkpoint(ckpt)
    assert meta["step"] == 3 and meta["note"] == "mid"
    part2 = [float(step2(paddle.to_tensor(i), paddle.to_tensor(l)))
             for i, l in batches[3:]]

    np.testing.assert_allclose(part1 + part2, straight, rtol=1e-6, atol=1e-6)
    final_resumed = {k: np.asarray(v._data)
                     for k, v in model2.state_dict().items()}
    for k in final_straight:
        np.testing.assert_array_equal(final_straight[k], final_resumed[k])


def test_kill_resume_with_dropout_rng(tmp_path):
    """The rng stream is part of the checkpoint: resume with dropout active
    still reproduces the uninterrupted trajectory."""
    def build():
        cfg = models.GPTConfig(vocab_size=64, hidden_size=32,
                               num_hidden_layers=2, num_attention_heads=4,
                               max_position_embeddings=32,
                               hidden_dropout_prob=0.2,
                               attention_probs_dropout_prob=0.0)
        model = models.GPTForPretraining(cfg)
        crit = models.GPTPretrainingCriterion()
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=model.parameters())
        from paddle_tpu.jit import TrainStep
        return TrainStep(model, lambda l, y: crit(l, y), opt), model

    batches = _batches(4, seed=5)
    paddle.seed(11)
    step, _ = build()
    straight = [float(step(paddle.to_tensor(i), paddle.to_tensor(l)))
                for i, l in batches]

    ckpt = str(tmp_path / "ck")
    paddle.seed(11)
    step1, _ = build()
    part1 = [float(step1(paddle.to_tensor(i), paddle.to_tensor(l)))
             for i, l in batches[:2]]
    step1.save_checkpoint(ckpt)
    del step1

    paddle.seed(777)  # must be overridden by the restored rng stream
    step2, _ = build()
    step2.restore_checkpoint(ckpt)
    part2 = [float(step2(paddle.to_tensor(i), paddle.to_tensor(l)))
             for i, l in batches[2:]]
    np.testing.assert_allclose(part1 + part2, straight, rtol=1e-6, atol=1e-6)


def test_stale_tmp_dir_does_not_break_manager(tmp_path):
    """Debris from a save killed mid-write must not wedge the manager
    (regression: int('000042.tmp') ValueError in all_steps)."""
    os.makedirs(tmp_path / "step-000000042.tmp-p00000")
    mgr = dck.CheckpointManager(str(tmp_path), save_interval_steps=1)
    assert mgr.all_steps() == []
    mgr.save({"a": jnp.zeros((4,), jnp.float32)}, 1)
    assert mgr.all_steps() == [1]
    assert not os.path.exists(tmp_path / "step-000000042.tmp-p00000")


def test_manager_retention_and_interval(tmp_path):
    mgr = dck.CheckpointManager(str(tmp_path), max_to_keep=2,
                                save_interval_steps=10)
    x = {"a": jnp.zeros((4,), jnp.float32)}
    assert not mgr.should_save(5)
    for s in (10, 20, 30):
        assert mgr.should_save(s)
        mgr.save(x, s)
    assert mgr.all_steps() == [20, 30]
    assert not mgr.should_save(35)


def test_train_epoch_range_resumes(tmp_path):
    mgr = dck.CheckpointManager(str(tmp_path))
    x = {"a": jnp.zeros((4,), jnp.float32)}
    done = []
    for e in dck.train_epoch_range(5, mgr):
        done.append(e)
        mgr.save(x, step=e * 100, extra_meta={"epoch": e})
        if e == 2:
            break  # simulated preemption
    assert done == [0, 1, 2]
    resumed = list(dck.train_epoch_range(5, dck.CheckpointManager(str(tmp_path))))
    assert resumed == [3, 4]


def test_lr_scheduler_state_survives_resume(tmp_path):
    """A resumed run must continue the LR schedule, not restart warmup."""
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.optimizer import lr as lr_mod

    def make():
        paddle.seed(0)
        model, crit = _gpt_tiny()
        sched = lr_mod.StepDecay(learning_rate=0.1, step_size=2, gamma=0.5)
        opt = paddle.optimizer.Adam(learning_rate=sched,
                                    parameters=model.parameters())
        return TrainStep(model, lambda l, y: crit(l, y), opt), sched

    batches = _batches(5)
    step, sched = make()
    for ids, lbl in batches:
        step(paddle.to_tensor(ids), paddle.to_tensor(lbl))
        sched.step()
    step.save_checkpoint(str(tmp_path), step=5)
    lr_before = sched()

    step2, sched2 = make()
    meta = step2.restore_checkpoint(str(tmp_path))
    assert meta["step"] == 5
    assert sched2.last_epoch == sched.last_epoch
    assert abs(sched2() - lr_before) < 1e-12


def test_restore_ignores_stale_higher_numbered_shards(tmp_path):
    """A re-save from fewer processes must not overlay stale shard files."""
    tree = {"w": jnp.arange(8.0)}
    dck.save_sharded(tree, str(tmp_path), step=7)
    step_dir = os.path.join(str(tmp_path), "step-000000007")
    # forge a stale shard file from a previous higher-process-count save
    np.savez(os.path.join(step_dir, "shards-p00003.npz"),
             **{"w@0": np.full(8, 999.0, np.float32)})
    out, step, _ = dck.restore_sharded(str(tmp_path))
    np.testing.assert_allclose(np.asarray(out["w"]), np.arange(8.0))
