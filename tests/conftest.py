"""Test config: run everything on a virtual 8-device CPU mesh
(SURVEY.md §4 implication (c): multi-device tests without hardware via
xla_force_host_platform_device_count)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force CPU even if the shell exports axon/tpu
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags +
                               " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# the axon sitecustomize registers the TPU backend at interpreter start and
# pins jax_platforms; override it before any backend is initialized.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as paddle
    np.random.seed(0)
    paddle.seed(0)
    yield
