"""Test config: run everything on a virtual 8-device CPU mesh
(SURVEY.md §4 implication (c): multi-device tests without hardware via
xla_force_host_platform_device_count)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force CPU even if the shell exports axon/tpu
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags +
                               " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# the axon sitecustomize registers the TPU backend at interpreter start and
# pins jax_platforms; override it before any backend is initialized.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture()
def cpu8_env():
    """Subprocess environment for mesh/probe tests: a CPU-pinned copy of
    os.environ with the 8-virtual-device XLA flag set — the ONE place the
    `xla_force_host_platform_device_count` incantation lives for tests
    (probes/bench previously each hand-rolled it).  Subprocess-isolated:
    mutating the returned dict never touches this process."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never grab the TPU tunnel
    env["JAX_PLATFORMS"] = "cpu"
    env_flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in env_flags:
        env["XLA_FLAGS"] = (env_flags +
                            " --xla_force_host_platform_device_count=8"
                            ).strip()
    return env


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as paddle
    from paddle_tpu.core import op as _core_op
    np.random.seed(0)
    paddle.seed(0)
    # fresh dispatch cache per test: a cached entry bakes module state read
    # at trace time, so monkeypatched kernels/flags from one test must not
    # leak compiled executables into the next (within-test caching keeps
    # the eager fast path exercised)
    _core_op.dispatch_cache_clear()
    yield


# Tests measured >= ~8s on the 1-core bench host (dominated by shard_map /
# big-model XLA compiles and multi-process IO).  Centralized here so the fast
# tier (`pytest -m "not slow"`) stays under 5 minutes single-core; the full
# suite remains the green-ness bar.
_SLOW = {
    "test_vgg_and_mobilenet_forward", "test_ptq_lenet_within_one_percent",
    "test_ring_attention_matches_naive",
    "test_varlen_bert_trains_with_masked_flash_attention",
    "test_resnet_train_step", "test_mp_dataloader_correct_and_ordered",
    "test_kill_resume_with_dropout_rng",
    "test_mp_dataloader_no_shm_leak_on_early_break", "test_resnet_forward",
    "test_run_steps_matches_per_call_steps",
    "test_gradient_merge_matches_large_batch",
    "test_dropout_statistics_and_determinism",
    "test_expert_parallel_step_matches_single_device",
    "test_bert_train_step_loss_decreases", "test_kill_resume_bit_exact",
    "test_sharded_step_matches_single_device",
    "test_full_routing_matches_dense_mixture",
    "test_pipeline_parallel_matches_single_device",
    "test_pipeline_1f1b_matches_gpipe_grads", "test_moe_grad_numeric",
    "test_qat_trains_and_tracks_fp32_accuracy", "test_gpt_forward_and_train",
    "test_recompute_matches", "test_pipeline_1f1b_matches_single_device",
    "test_mp_dataloader_parallel_speedup",
    "test_gpt_kv_cache_decode_matches_full", "test_aux_loss_uniform_is_one",
    "test_mp_dataloader_concurrent_iterators",
    "test_spawn_multiprocess_smoke", "test_model_fit_eval_predict",
    "test_qat_save_quantized_model_roundtrip",
    "test_mp_dataloader_early_break_then_new_epoch_no_stale_batches",
    "test_capacity_drops_no_nan", "test_pipeline_respects_frozen_params",
    "test_lr_scheduler_state_survives_resume", "test_rnn_layers",
    "test_transformer_full", "test_allreduce_prod_signs_and_zeros",
    "test_qat_per_tensor_weight_quant_option",
    "test_sequence_concat_and_enumerate_and_expand",
    # round-3 additions over ~5s (grad sweeps / scan-compile heavy)
    "test_yolo_loss_grad_flows", "test_generate_greedy_matches_eager_argmax",
    "test_generate_all_finished_early_exit_parity",
    "test_generate_beam_matches_numpy_oracle",
    "test_deform_conv2d_grads_numeric", "test_bert_forward_shapes",
    "test_generate_topk1_matches_greedy_and_seeded_sampling_reproducible",
    "test_beam_decoder_dynamic_decode_gru",
    "test_yolo_loss_matches_numpy_reference", "test_model_summary",
    "test_fleet_facade",
    "test_train_step_sparse_first_step_matches_dense_and_learns",
    "test_data_parallel_wrapper", "test_collectives_under_shard_map",
    "test_callbacks_early_stopping", "test_adamw_rmsprop_etc_run",
    "test_data_parallel_eager_reducer_parity",
    "test_generate_eos_padding_and_score", "test_gpt_causal",
    "test_gpt_chunked_decode_matches_full", "test_standalone_c_binary",
    "test_standalone_c_train_binary", "test_train_session_python_side",
    "test_crf_trains_to_recover_transitions",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.name.split("[")[0] in _SLOW:
            item.add_marker(pytest.mark.slow)
