"""Dataset breadth (Conll05st/WMT14/WMT16/Movielens/VOC2012/Flowers) and
paddle.regularizer — VERDICT r1 missing #9.

Each test builds a tiny synthetic archive in the reference's exact layout
and checks parsing + item shapes (reference test strategy: the dataset
unit tests feed golden mini-fixtures)."""
import gzip
import io
import os
import tarfile
import zipfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.text import Conll05st, WMT14, WMT16, Movielens


def _add_bytes(tf, name, data):
    info = tarfile.TarInfo(name)
    info.size = len(data)
    tf.addfile(info, io.BytesIO(data))


def test_conll05st(tmp_path):
    words = "The\ncat\nsat\n\nDogs\nbark\n\n".encode()
    # props: one predicate column; '-' rows for non-predicates
    props = ("-\t(A0*\nsit\t*)\n-\t(V*)\n\n"
             "-\t(V*)\nbark\t*\n\n").encode()
    # NOTE the props format is token-per-line columns; build precisely:
    props = (b"-\t(A0*\n" b"sit\t*)\n" b"-\t(V*)\n" b"\n"
             b"bark\t(V*)\n" b"-\t*\n" b"\n")
    data = tmp_path / "conll05st-tests.tar.gz"
    with tarfile.open(data, "w:gz") as tf:
        _add_bytes(tf, "conll05st-release/test.wsj/words/test.wsj.words.gz",
                   gzip.compress(words))
        _add_bytes(tf, "conll05st-release/test.wsj/props/test.wsj.props.gz",
                   gzip.compress(props))
    wd = tmp_path / "wordDict.txt"
    wd.write_text("The\ncat\nsat\nDogs\nbark\n")
    vd = tmp_path / "verbDict.txt"
    vd.write_text("sit\nbark\n")
    td = tmp_path / "targetDict.txt"
    td.write_text("B-A0\nI-A0\nB-V\nI-V\nO\n")
    ds = Conll05st(data_file=str(data), word_dict_file=str(wd),
                   verb_dict_file=str(vd), target_dict_file=str(td))
    assert len(ds) == 2
    item = ds[0]
    assert len(item) == 9  # words + 5 ctx + predicate + mark + labels
    n = len(item[0])
    assert all(len(a) == n for a in item)
    w, p, l = ds.get_dict()
    assert "O" in l and all(t in l for t in ("B-A0", "I-A0", "B-V", "I-V"))


def _wmt_pairs():
    return [("hello world", "bonjour monde"),
            ("good day", "bonne journee"),
            ("the cat", "le chat")]


def test_wmt14(tmp_path):
    data = tmp_path / "wmt14.tgz"
    vocab_src = "\n".join(["<s>", "<e>", "<unk>", "hello", "world", "good",
                           "day", "the", "cat"]).encode()
    vocab_trg = "\n".join(["<s>", "<e>", "<unk>", "bonjour", "monde",
                           "bonne", "journee", "le", "chat"]).encode()
    body = "\n".join(f"{s}\t{t}" for s, t in _wmt_pairs()).encode()
    with tarfile.open(data, "w:gz") as tf:
        _add_bytes(tf, "wmt14/src.dict", vocab_src)
        _add_bytes(tf, "wmt14/trg.dict", vocab_trg)
        _add_bytes(tf, "wmt14/train/train", body)
    ds = WMT14(data_file=str(data), mode="train", dict_size=30)
    assert len(ds) == 3
    src, trg, nxt = ds[0]
    assert src[0] == ds.src_dict["<s>"] and src[-1] == ds.src_dict["<e>"]
    assert trg[0] == ds.trg_dict["<s>"] and nxt[-1] == ds.trg_dict["<e>"]
    np.testing.assert_array_equal(trg[1:], nxt[:-1])
    fwd, _ = ds.get_dict()
    rev_s, _ = ds.get_dict(reverse=True)
    assert rev_s[fwd["hello"]] == "hello"


def test_wmt16(tmp_path):
    data = tmp_path / "wmt16.tar.gz"
    body = "\n".join(f"{s}\t{t}" for s, t in _wmt_pairs()).encode()
    with tarfile.open(data, "w:gz") as tf:
        _add_bytes(tf, "wmt16/train", body)
        _add_bytes(tf, "wmt16/val", body[:20])
        _add_bytes(tf, "wmt16/test", body)
    ds = WMT16(data_file=str(data), mode="train", src_dict_size=20,
               trg_dict_size=20, lang="en")
    assert len(ds) == 3
    src, trg, nxt = ds[1]
    assert src[0] == ds.src_dict["<s>"] and src[-1] == ds.src_dict["<e>"]
    np.testing.assert_array_equal(trg[1:], nxt[:-1])
    # de->en direction swaps columns
    ds_de = WMT16(data_file=str(data), mode="train", src_dict_size=20,
                  trg_dict_size=20, lang="de")
    assert len(ds_de) == 3
    assert "bonjour" in ds_de.src_dict and "hello" in ds_de.trg_dict


def test_movielens(tmp_path):
    data = tmp_path / "ml-1m.zip"
    with zipfile.ZipFile(data, "w") as z:
        z.writestr("ml-1m/movies.dat",
                   "1::Toy Story (1995)::Animation|Children's\n"
                   "2::Jumanji (1995)::Adventure\n")
        z.writestr("ml-1m/users.dat",
                   "1::F::1::10::48067\n2::M::56::16::70072\n")
        z.writestr("ml-1m/ratings.dat",
                   "1::1::5::978300760\n1::2::3::978302109\n"
                   "2::1::4::978301968\n")
    tr = Movielens(data_file=str(data), mode="train", test_ratio=0.0)
    assert len(tr) == 3
    uid, g, a, j, mid, cats, title, rating = tr[0]
    assert rating.dtype == np.float32
    assert title.ndim == 1 and cats.ndim == 1
    te = Movielens(data_file=str(data), mode="test", test_ratio=1.0)
    assert len(te) == 3


def _png_bytes(arr, mode="RGB"):
    from PIL import Image
    buf = io.BytesIO()
    Image.fromarray(arr, mode).save(buf, "PNG")
    return buf.getvalue()


def _jpg_bytes(arr):
    from PIL import Image
    buf = io.BytesIO()
    Image.fromarray(arr, "RGB").save(buf, "JPEG")
    return buf.getvalue()


def test_voc2012(tmp_path):
    from paddle_tpu.vision.datasets import VOC2012
    rng = np.random.RandomState(0)
    data = tmp_path / "voc.tar"
    with tarfile.open(data, "w") as tf:
        keys = ["2007_000001", "2007_000002"]
        _add_bytes(tf, "VOCdevkit/VOC2012/ImageSets/Segmentation/"
                   "trainval.txt", "\n".join(keys).encode() + b"\n")
        _add_bytes(tf, "VOCdevkit/VOC2012/ImageSets/Segmentation/val.txt",
                   keys[0].encode() + b"\n")
        for k in keys:
            img = rng.randint(0, 255, (8, 8, 3), "uint8")
            lbl = rng.randint(0, 21, (8, 8), "uint8")
            _add_bytes(tf, f"VOCdevkit/VOC2012/JPEGImages/{k}.jpg",
                       _jpg_bytes(img))
            _add_bytes(tf, f"VOCdevkit/VOC2012/SegmentationClass/{k}.png",
                       _png_bytes(lbl, "L"))
    ds = VOC2012(data_file=str(data), mode="train")
    assert len(ds) == 2
    img, lbl = ds[0]
    assert img.shape == (8, 8, 3) and lbl.shape == (8, 8)
    assert lbl.dtype == np.uint8
    assert len(VOC2012(data_file=str(data), mode="valid")) == 1


def test_flowers(tmp_path):
    import scipy.io as scio
    from paddle_tpu.vision.datasets import Flowers
    rng = np.random.RandomState(0)
    data = tmp_path / "102flowers.tgz"
    n = 4
    with tarfile.open(data, "w:gz") as tf:
        for i in range(1, n + 1):
            img = rng.randint(0, 255, (8, 8, 3), "uint8")
            _add_bytes(tf, "jpg/image_%05d.jpg" % i, _jpg_bytes(img))
    labels = tmp_path / "imagelabels.mat"
    scio.savemat(labels, {"labels": np.arange(1, n + 1)[None]})
    setid = tmp_path / "setid.mat"
    scio.savemat(setid, {"tstid": np.array([[1, 2, 3]]),
                         "trnid": np.array([[4]]),
                         "valid": np.array([[2]])})
    tr = Flowers(data_file=str(data), label_file=str(labels),
                 setid_file=str(setid), mode="train")
    assert len(tr) == 3
    img, lab = tr[0]
    assert img.shape == (8, 8, 3) and lab.shape == (1,)
    assert len(Flowers(data_file=str(data), label_file=str(labels),
                       setid_file=str(setid), mode="test")) == 1


def test_regularizer_l2_matches_float_and_l1_sign():
    from paddle_tpu.regularizer import L1Decay, L2Decay
    x = paddle.ones([2, 2])

    def one_step(wd):
        paddle.seed(0)
        lin = paddle.nn.Linear(2, 2)
        w0 = lin.weight.numpy().copy()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin.parameters(),
                                   weight_decay=wd)
        loss = lin(x).sum()
        loss.backward()
        opt.step()
        return w0, lin.weight.numpy()

    w0a, wa = one_step(0.5)
    w0b, wb = one_step(L2Decay(0.5))
    np.testing.assert_allclose(wa, wb, rtol=1e-6)

    w0c, wc = one_step(L1Decay(0.5))
    # grad of sum = x^T 1 = [2,2] per out; manual: g + 0.5*sign(w)
    g = np.full((2, 2), 2.0, "float32")
    ref = w0c - 0.1 * (g + 0.5 * np.sign(w0c))
    np.testing.assert_allclose(wc, ref, rtol=1e-5, atol=1e-6)


def test_param_attr_regularizer_overrides_optimizer_decay():
    from paddle_tpu.regularizer import L2Decay
    x = paddle.ones([2, 2])
    paddle.seed(0)
    lin = paddle.nn.Linear(2, 2, weight_attr=paddle.nn.ParamAttr(
        regularizer=L2Decay(0.0)))  # per-param: NO decay on the weight
    w0 = lin.weight.numpy().copy()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters(),
                               weight_decay=0.5)  # global decay
    lin(x).sum().backward()
    opt.step()
    g = np.full((2, 2), 2.0, "float32")
    ref = w0 - 0.1 * g  # decay suppressed by the per-param override
    np.testing.assert_allclose(lin.weight.numpy(), ref, rtol=1e-5,
                               atol=1e-6)
