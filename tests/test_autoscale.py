"""Train->serve loop (ISSUE 18): continuous weight refresh with a
canary gate and rollback-safe convergence (serving/refresh.py), plus
SLO-driven elastic membership (serving/autoscaler.py).

Tier-1 keeps the fleet tests small (tiny GPT, <= 2 worker processes)
under a hard SIGALRM per-test timeout; the diurnal replay and the full
chaos matrix live in probes/elastic_probe.py (bench `detail.elastic`).
"""
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import models
from paddle_tpu.core.errors import InvalidArgumentError
from paddle_tpu.jit import state_arrays
from paddle_tpu.serving import (Autoscaler, FleetRouter, FleetRefresher,
                                ServingEngine, ServingGateway,
                                WeightPublisher, latest_publish)
from paddle_tpu.serving.fleet import (DRAINING, HEALTHY, ReplicaManager)
from paddle_tpu.serving.transfer import file_sha256
from paddle_tpu.utils import faults

pytestmark = pytest.mark.autoscale

GPT_KW = dict(vocab_size=64, hidden_size=32, num_hidden_layers=2,
              num_attention_heads=2, hidden_dropout_prob=0.0,
              attention_probs_dropout_prob=0.0,
              max_position_embeddings=128)
ENGINE_KW = dict(max_slots=2, max_len=64, prefill_buckets=(8,),
                 decode_chunk=2)
SEED_OLD, SEED_NEW, SEED_BAD, SEED_DIV = 11, 99, 13, 77


def worker_spec(**engine_overrides):
    ekw = dict(ENGINE_KW, **engine_overrides)
    ekw["prefill_buckets"] = list(ekw["prefill_buckets"])
    return {"model": {"factory": "paddle_tpu.serving.worker:build_gpt",
                      "kwargs": dict(GPT_KW, seed=SEED_OLD)},
            "engine": ekw}


_model_cache = {}


def tiny_model(seed=SEED_OLD):
    """One model instance per seed: engines sharing it share compiled
    programs (the test_fleet _model_cache pattern), which keeps this
    file inside the tier-1 time budget."""
    m = _model_cache.get(seed)
    if m is None:
        paddle.seed(seed)
        m = models.GPTForPretraining(models.GPTConfig(**GPT_KW))
        m.eval()
        _model_cache[seed] = m
    return m


def tiny_engine(seed=SEED_OLD, **overrides):
    return ServingEngine(tiny_model(seed), **dict(ENGINE_KW, **overrides))


def oracle(model, prompt, max_new):
    out, _ = model.generate(paddle.to_tensor(np.asarray(prompt)[None]),
                            max_new_tokens=max_new)
    return np.asarray(out.numpy())[0].tolist()


@pytest.fixture
def hard_timeout():
    """Tier-1 wedge guard: SIGALRM aborts the test outright if a flip
    or worker hang ever leaks past the in-test timeouts."""
    def handler(signum, frame):
        raise TimeoutError("autoscale hard per-test timeout (a flip or "
                           "worker hang leaked past in-test timeouts)")
    old = signal.signal(signal.SIGALRM, handler)
    signal.alarm(150)
    yield
    signal.alarm(0)
    signal.signal(signal.SIGALRM, old)


@pytest.fixture
def guard():
    """Closes every registered fleet/engine/refresher at teardown and
    disarms faults — a failing test leaves no orphans behind."""
    items = []
    yield items.append
    for item in reversed(items):
        try:
            item.close()
        except Exception:
            pass
    faults.reset()


@pytest.fixture
def remote_worker():
    """Standalone `--listen` worker on an ephemeral loopback port."""
    procs = []

    def spawn(index=0):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = (root + os.pathsep + env["PYTHONPATH"]
                             if env.get("PYTHONPATH") else root)
        proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.serving.worker",
             "--listen", "127.0.0.1:0", "--index", str(index)],
            stdin=subprocess.DEVNULL, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, env=env,
            start_new_session=True)
        procs.append(proc)
        while True:  # SIGALRM guards the wait
            line = proc.stdout.readline()
            if not line:
                raise AssertionError("remote worker exited early")
            if "worker listening on" in line:
                addr = line.strip().rsplit(" ", 1)[-1]
                break
        threading.Thread(target=lambda: proc.stdout.read(),
                         daemon=True).start()
        return addr, proc

    yield spawn
    for p in procs:
        try:
            p.kill()
            p.wait(timeout=10)
        except Exception:
            pass


def wait_for(pred, timeout, what):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return
        time.sleep(0.005)
    raise AssertionError(f"timed out after {timeout}s waiting for {what}")


# ---------------------------------------------------------------------------
# publisher: atomic publishes + the corrupt-publish chaos knob
# ---------------------------------------------------------------------------

def test_publisher_atomic_latest_and_corrupt_knob(tmp_path, guard):
    d = str(tmp_path / "pub")
    pub = WeightPublisher(d)
    state = {"w/a": np.arange(8, dtype=np.float32),
             "w/b": np.ones((2, 3), dtype=np.float32)}
    assert latest_publish(d) is None
    p0 = pub.publish(state=state)
    assert p0["step"] == 0
    got = latest_publish(d)
    assert got is not None and got["sha256"] == p0["sha256"]
    # the manifest sha matches the visible bytes (no fault armed)
    assert file_sha256(got["path"]) == got["sha256"]
    # round-trips with keys intact
    with np.load(got["path"], allow_pickle=False) as z:
        assert sorted(z.files) == sorted(state)
    # auto-incrementing steps; LATEST follows
    p1 = pub.publish(state=state)
    assert p1["step"] == 1
    assert latest_publish(d)["step"] == 1
    # numbering resumes past what's on disk
    assert WeightPublisher(d).publish(state=state)["step"] == 2
    # a publisher crash mid-write leaves only an invisible tmp dir:
    # nothing but push-* dirs are ever considered
    os.makedirs(os.path.join(d, ".push-000000099.tmp-1"))
    assert latest_publish(d)["step"] == 2

    # PDTPU_FAULT_PUBLISH_CORRUPT bit-rots the artifact AFTER the
    # rename, so the manifest still carries the good-bytes sha and the
    # mismatch is detectable — corruption can never ride in silently
    faults.enable("publish_corrupt", "1")
    p3 = pub.publish(state=state)
    assert file_sha256(p3["path"]) != p3["sha256"]
    faults.disable("publish_corrupt")
    p4 = pub.publish(state=state)  # knob names ONE publish, not all
    assert file_sha256(p4["path"]) == p4["sha256"]


def test_publisher_rejects_ambiguous_args(tmp_path):
    pub = WeightPublisher(str(tmp_path))
    with pytest.raises(InvalidArgumentError):
        pub.publish()
    with pytest.raises(InvalidArgumentError):
        pub.publish(model=object(), state={})


# ---------------------------------------------------------------------------
# engine.swap_weights: the zero-recompile primitive
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_swap_weights_bit_identity_and_zero_recompiles(guard):
    eng = tiny_engine(SEED_OLD)
    guard(eng)
    eng.warmup()
    prompt = [1, 2, 3]
    resp = eng.submit(prompt, max_new_tokens=10)
    eng.run_until_drained(timeout=60)
    assert resp.tokens() == oracle(tiny_model(SEED_OLD), prompt, 10)
    assert eng.weights_sha is None and eng.refresh_epoch == 0

    new_state = {k: np.asarray(v)
                 for k, v in state_arrays(tiny_model(SEED_NEW)).items()}
    eng.swap_weights(new_state, "shaNEW")
    assert eng.weights_sha == "shaNEW" and eng.refresh_epoch == 1
    resp2 = eng.submit(prompt, max_new_tokens=10)
    eng.run_until_drained(timeout=60)
    assert resp2.tokens() == oracle(tiny_model(SEED_NEW), prompt, 10)
    # the flip reused every compiled program
    assert eng.post_warmup_compiles() == 0

    # a state dict that does not fit the model is rejected ATOMICALLY:
    # typed error, old weights keep serving
    bad = dict(new_state)
    missing_key = sorted(bad)[0]
    del bad[missing_key]
    with pytest.raises(InvalidArgumentError):
        eng.swap_weights(bad, "shaBAD")
    wrong = dict(new_state)
    wrong[missing_key] = np.zeros((3, 3), dtype=np.float32)
    with pytest.raises(InvalidArgumentError):
        eng.swap_weights(wrong, "shaBAD")
    assert eng.weights_sha == "shaNEW" and eng.refresh_epoch == 1
    resp3 = eng.submit(prompt, max_new_tokens=10)
    eng.run_until_drained(timeout=60)
    assert resp3.tokens() == resp2.tokens()


# ---------------------------------------------------------------------------
# ISSUE-18 satellite: remove() of a mid-drain replica is idempotent
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_remove_mid_drain_idempotent_hammer(guard):
    mgr = ReplicaManager()
    guard(type("_Closer", (), {"close": staticmethod(mgr.close_all)})())
    r0 = mgr.add(tiny_engine(SEED_OLD))
    r1 = mgr.add(tiny_engine(SEED_OLD))
    mgr.warm_all()
    # park a long-running stream on r0 so the drain cannot finish
    # instantly
    req, resp = r0.engine.make_request([1, 2, 3], 24)
    r0.engine.scheduler.submit(req, resp)
    mgr.drain(r0.id)
    assert r0.state == DRAINING
    # the hammer: remove() during the drain must neither raise, nor
    # yank the replica out from under its residents, nor double-close
    for _ in range(25):
        mgr.remove(r0.id)
    assert mgr.get(r0.id) is r0       # still draining, removal deferred
    assert r0.remove_after_drain
    t0 = time.monotonic()
    while ((mgr.get(r0.id) is not None or not resp.done())
           and time.monotonic() - t0 < 90):
        mgr.tick()
        time.sleep(0.002)
    assert mgr.get(r0.id) is None      # drained, THEN removed
    # the stream survived (finished in place or migrated to r1)
    assert resp.done() and resp.error is None
    assert len(resp.tokens()) == 24
    # removing an already-removed replica stays a no-op
    mgr.remove(r0.id)
    assert [r.id for r in mgr.replicas()] == [r1.id]


# ---------------------------------------------------------------------------
# the full refresh loop on an in-process fleet
# ---------------------------------------------------------------------------

# Engine-level tests in this file are full-tier only: each pays 7-10s of
# warmup compile and the repo-wide tier-1 run is already near its wall-time
# budget.  Tier-1 keeps the sub-second unit tests (publisher contract,
# autoscaler hysteresis on a fake fleet) plus the healthz gate below.
@pytest.mark.slow
def test_fleet_refresh_flip_and_rollback_inprocess(
        hard_timeout, guard, tmp_path):
    prompt = [1, 2, 3]
    want_new = oracle(tiny_model(SEED_NEW), prompt, 10)

    # oracle warms first: its compiles land before the fleet's marks
    orc = tiny_engine(SEED_OLD)
    guard(orc)
    orc.warmup()
    fleet = FleetRouter([tiny_engine(SEED_OLD), tiny_engine(SEED_OLD)])
    guard(fleet)
    fleet.warmup()
    fleet.start()
    pubdir = str(tmp_path / "push")
    refresher = FleetRefresher(fleet, pubdir, orc,
                               canary_prompts=(prompt,),
                               canary_max_new_tokens=10)
    guard(refresher)
    publisher = WeightPublisher(pubdir)

    def shas():
        return [getattr(r.engine, "weights_sha", None)
                for r in fleet.manager.replicas((HEALTHY,))]

    # admitted BEFORE the publish: finishes on the old weights
    resp_pre = fleet.submit(prompt, 24)
    pub = publisher.publish(state=state_arrays(tiny_model(SEED_NEW)))

    def converged(sha):
        refresher.poll()
        s = shas()
        return len(s) == 2 and all(x == sha for x in s)

    wait_for(lambda: converged(pub["sha256"]), 90,
             "both replicas on the published weights")
    assert resp_pre.tokens(timeout=60) == oracle(tiny_model(SEED_OLD),
                                                 prompt, 24)
    for rep in fleet.manager.replicas((HEALTHY,)):
        req, resp = rep.engine.make_request(prompt, 10)
        rep.engine.scheduler.submit(req, resp)
        fleet._work.set()
        assert resp.tokens(timeout=60) == want_new
    assert fleet.post_warmup_compiles() == 0

    # corrupt publish: quarantined at the artifact gate, nothing flips
    faults.enable("publish_corrupt", "1")
    bad = publisher.publish(state=state_arrays(tiny_model(SEED_BAD)))
    faults.disable("publish_corrupt")
    refresher.poll()
    assert bad["sha256"] in refresher.status()["quarantined"]
    assert all(x == pub["sha256"] for x in shas())

    # diverging canary: rolls back + reconverges on verified weights
    faults.enable("canary_diverge")
    div = publisher.publish(state=state_arrays(tiny_model(SEED_DIV)))
    refresher.poll()
    faults.disable("canary_diverge")
    assert div["sha256"] in refresher.status()["quarantined"]
    wait_for(lambda: converged(pub["sha256"]), 90,
             "rollback convergence onto the last verified weights")
    assert fleet.manager.counters()["rollbacks"] >= 2
    assert fleet.post_warmup_compiles() == 0
    assert fleet.health()["routable_verified"] == 2


# ---------------------------------------------------------------------------
# the full loop on a MIXED fleet: in-process + subprocess + remote
# (two worker-process boots: full-tier only, the in-process tier-1 test
# above covers the same choreography inside the time budget)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_mixed_fleet_refresh_rollback_and_bit_identity(
        hard_timeout, guard, remote_worker, tmp_path):
    prompt = [1, 2, 3]
    want_old = oracle(tiny_model(SEED_OLD), prompt, 24)
    want_new = oracle(tiny_model(SEED_NEW), prompt, 10)

    # the oracle warms FIRST: its compiles land in the global registry
    # before the fleet takes its warmup marks, so the zero-post-warmup
    # assertion below measures only the flips
    orc = tiny_engine(SEED_OLD)
    guard(orc)
    orc.warmup()

    fleet = FleetRouter([tiny_engine(SEED_OLD)], heartbeat_timeout_s=30.0)
    guard(fleet)
    fleet.add_worker(worker_spec(), boot_timeout_s=180.0)
    addr, _proc = remote_worker()
    fleet.add_worker(worker_spec(), address=addr, boot_timeout_s=180.0,
                     manager_silence_s=30.0, ack_timeout_s=30.0)
    fleet.warmup()
    fleet.start()
    pubdir = str(tmp_path / "push")
    refresher = FleetRefresher(fleet, pubdir, orc,
                               canary_prompts=(prompt,),
                               canary_max_new_tokens=10,
                               flip_timeout_s=90.0)
    guard(refresher)
    publisher = WeightPublisher(pubdir)

    def shas():
        return [getattr(r.engine, "weights_sha", None)
                for r in fleet.manager.replicas((HEALTHY,))]

    # a stream admitted BEFORE the publish finishes on the old weights —
    # the flip fences admissions but never a resident run
    resp_pre = fleet.submit(prompt, 24)

    pub = publisher.publish(state=state_arrays(tiny_model(SEED_NEW)))
    refresher.poll()
    assert refresher.status()["current_sha"] == pub["sha256"]

    def converged(sha):
        refresher.poll()  # convergence sweep for stragglers
        s = shas()
        return len(s) == 3 and all(x == sha for x in s)

    wait_for(lambda: converged(pub["sha256"]), 120,
             "every replica on the published weights")
    assert resp_pre.tokens(timeout=60) == want_old  # pre-flip stream
    # post-flip: every replica serves streams bit-identical to the
    # new-weights oracle, with zero post-warmup compiles fleet-wide
    for rep in fleet.manager.replicas((HEALTHY,)):
        req, resp = rep.engine.make_request(prompt, 10)
        rep.engine.scheduler.submit(req, resp)
        fleet._work.set()
        assert resp.tokens(timeout=90) == want_new
    assert fleet.post_warmup_compiles() == 0
    health = fleet.health()
    assert health["routable_verified"] == 3
    assert health["refresh"]["current_sha"] == pub["sha256"]

    # -- corrupt publish: quarantined at the artifact gate, nothing
    # flips, the fleet keeps serving the verified weights
    faults.enable("publish_corrupt", "1")
    bad = publisher.publish(state=state_arrays(tiny_model(SEED_BAD)))
    faults.disable("publish_corrupt")
    refresher.poll()
    st = refresher.status()
    assert bad["sha256"] in st["quarantined"]
    assert st["current_sha"] == pub["sha256"]
    assert all(x == pub["sha256"] for x in shas())

    # -- canary-diverging publish: flips ONE canary, the forced
    # mismatch rolls it back, and the fleet converges onto the last
    # verified weights on every replica
    faults.enable("canary_diverge")
    div = publisher.publish(state=state_arrays(tiny_model(SEED_DIV)))
    refresher.poll()
    faults.disable("canary_diverge")
    st = refresher.status()
    assert div["sha256"] in st["quarantined"]
    wait_for(lambda: converged(pub["sha256"]), 120,
             "rollback convergence onto the last verified weights")
    for rep in fleet.manager.replicas((HEALTHY,)):
        req, resp = rep.engine.make_request(prompt, 10)
        rep.engine.scheduler.submit(req, resp)
        fleet._work.set()
        assert resp.tokens(timeout=90) == want_new
    assert fleet.manager.counters()["rollbacks"] >= 2
    assert fleet.manager.counters()["weight_refreshes"] >= 3
    assert fleet.post_warmup_compiles() == 0
    assert fleet.health()["routable_verified"] == 3


# ---------------------------------------------------------------------------
# autoscaler decision unit (injected clock, fake fleet)
# ---------------------------------------------------------------------------

class _FakeRep:
    def __init__(self, rid):
        self.id = rid
        self.state = HEALTHY
        self.flipping = False
        self._load = 0

    def load(self):
        return self._load


class _FakeManager:
    def __init__(self):
        self.reps = {}
        self.scales = []
        self.target = None

    def replicas(self, states=None):
        return [r for r in self.reps.values()
                if states is None or r.state in states]

    def note_scale(self, up):
        self.scales.append("up" if up else "down")

    def set_target_replicas(self, n):
        self.target = n


class _FakeFleet:
    def __init__(self, n=1):
        self.manager = _FakeManager()
        self._next = 0
        self.removed = []
        for _ in range(n):
            self.spawn()

    def spawn(self):
        rid = self._next
        self._next += 1
        self.manager.reps[rid] = _FakeRep(rid)
        return rid

    def drain(self, rid):
        self.manager.reps[rid].state = DRAINING

    def remove(self, rid):
        # deferred remove-after-drain, like the real manager
        self.removed.append(rid)
        self.manager.reps.pop(rid, None)


def test_autoscaler_hysteresis_cooldown_and_bounds():
    clock = {"t": 0.0}
    sig = {"est_wait_s": 0.0, "queue_depth": 0, "shed_total": 0}
    fleet = _FakeFleet(n=1)
    asc = Autoscaler(fleet, lambda: dict(sig), fleet.spawn,
                     min_replicas=1, max_replicas=3,
                     scale_up_est_wait_s=0.5, breach_ticks=3,
                     idle_ticks=4, cooldown_s=10.0,
                     _clock=lambda: clock["t"])

    def live():
        return len([r for r in fleet.manager.reps.values()
                    if r.state != DRAINING])

    # hysteresis: two breached ticks move nothing, the third spawns
    sig["est_wait_s"] = 2.0
    assert asc.tick() is None and asc.tick() is None
    assert asc.tick() == "up" and live() == 2
    assert fleet.manager.scales == ["up"]
    # cooldown: sustained breach cannot spawn again until it elapses
    for _ in range(6):
        clock["t"] += 1.0
        assert asc.tick() is None
    # breach sustained THROUGH the cooldown: acts the moment it elapses
    clock["t"] += 10.0
    assert asc.tick() == "up" and live() == 3
    # bounds: at max_replicas, breach forever, no further spawns
    clock["t"] += 100.0
    for _ in range(8):
        clock["t"] += 1.0
        assert asc.tick() is None
    assert live() == 3

    # a calm tick resets the breach streak
    clock["t"] += 100.0
    sig["est_wait_s"] = 0.0
    asc.tick()
    assert asc.status()["breach_streak"] == 0
    # shed counters breach even with a low est-wait
    sig["shed_total"] = 5
    asc.tick()
    assert asc.status()["breach_streak"] == 1
    # a shed-free tick with an empty queue is idle — the opposing
    # streak resets (scale-down racing scale-up can never interleave)
    asc.tick()
    assert asc.status()["breach_streak"] == 0

    # idle ticks retire one replica per cooldown, draining — never
    # below min_replicas
    clock["t"] += 100.0
    downs = 0
    for _ in range(60):
        clock["t"] += 1.0
        if asc.tick() == "down":
            downs += 1
    assert downs == 2 and live() == 1
    assert fleet.manager.scales == ["up", "up", "down", "down"]
    assert fleet.manager.target == 1
    # drain-then-remove, never a kill: every retired replica went
    # through DRAINING before the deferred remove
    assert sorted(fleet.removed) == sorted(
        r for r in range(3) if r not in fleet.manager.reps)

    # a mid-flip replica is never picked as the victim
    fleet2 = _FakeFleet(n=2)
    for r in fleet2.manager.reps.values():
        r.flipping = True
    asc2 = Autoscaler(fleet2, lambda: dict(sig), fleet2.spawn,
                      min_replicas=1, max_replicas=3, idle_ticks=1,
                      cooldown_s=0.0, _clock=lambda: clock["t"])
    sig["est_wait_s"] = 0.0
    sig["shed_total"] = 0  # no fresh sheds for the new scaler
    for _ in range(5):
        clock["t"] += 1.0
        assert asc2.tick() is None  # wants down, but everyone is mid-flip
    assert len(fleet2.manager.reps) == 2


def test_autoscaler_rejects_bad_bounds():
    with pytest.raises(InvalidArgumentError):
        Autoscaler(_FakeFleet(), lambda: {}, lambda: None,
                   min_replicas=0, max_replicas=2)
    with pytest.raises(InvalidArgumentError):
        Autoscaler(_FakeFleet(), lambda: {}, lambda: None,
                   min_replicas=3, max_replicas=2)


# ---------------------------------------------------------------------------
# elastic membership against a REAL fleet (drain semantics end-to-end)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_autoscaler_scales_real_fleet_up_and_down(hard_timeout, guard):
    fleet = FleetRouter([tiny_engine(SEED_OLD)])
    guard(fleet)
    fleet.warmup()
    fleet.start()
    sig = {"est_wait_s": 0.0, "queue_depth": 0, "shed_total": 0}

    def spawn():
        eng = tiny_engine(SEED_OLD)
        eng.warmup()
        return fleet.add_replica(eng)

    asc = Autoscaler(fleet, lambda: dict(sig), spawn,
                     min_replicas=1, max_replicas=2,
                     scale_up_est_wait_s=0.5, breach_ticks=2,
                     idle_ticks=2, cooldown_s=0.0)
    sig["est_wait_s"] = 3.0
    asc.tick()
    assert asc.tick() == "up"
    wait_for(lambda: len(fleet.manager.routable()) == 2, 60,
             "spawned replica routable")
    # the new replica serves — and the gateway-visible counters moved
    resp = fleet.submit([1, 2, 3], 8)
    assert resp.tokens(timeout=60) == oracle(tiny_model(SEED_OLD),
                                             [1, 2, 3], 8)
    sig["est_wait_s"] = 0.0
    asc.tick()
    assert asc.tick() == "down"
    wait_for(lambda: len(fleet.manager.replicas((HEALTHY,))) == 1, 60,
             "drained replica reaped")
    c = fleet.manager.counters()
    assert c["scale_up"] == 1 and c["scale_down"] == 1
    # retirement was a drain: the fleet still serves
    resp2 = fleet.submit([1, 2, 3], 8)
    assert resp2.tokens(timeout=60) == oracle(tiny_model(SEED_OLD),
                                              [1, 2, 3], 8)


# ---------------------------------------------------------------------------
# gateway /healthz: 503 when no routable replica serves verified weights
# ---------------------------------------------------------------------------

class _FakeRefresher:
    def __init__(self):
        self.ok = True

    def sha_ok(self, sha):
        return self.ok

    def status(self):
        return {"current_sha": "deadbeef", "verified": 1,
                "quarantined": {}, "last_error": None}


def test_healthz_503_when_no_verified_replica(guard):
    fleet = FleetRouter([tiny_engine(SEED_OLD)])
    guard(fleet)
    fleet.warmup()
    gw = ServingGateway(fleet)
    guard(gw)
    fr = _FakeRefresher()
    fleet.attach_refresher(fr)
    status, _, body = gw.handle("GET", "/healthz")
    doc = json.loads(body)
    assert status == 200
    assert doc["fleet"]["routable_verified"] == 1
    assert doc["fleet"]["refresh"]["current_sha"] == "deadbeef"
    # replicas up, but NONE serving canary-passed weights: readiness
    # must fail — routing exists, verified capacity does not
    fr.ok = False
    status, _, body = gw.handle("GET", "/healthz")
    assert status == 503
    assert json.loads(body)["fleet"]["routable_verified"] == 0
    # scale signals the autoscaler polls are cheap and complete
    sig = gw.scale_signals()
    for key in ("est_wait_s", "queue_depth", "shed_total",
                "admitted_total"):
        assert key in sig
