"""Typed-error adoption at the public boundary (VERDICT r3 weak #5):
shape/dtype/argument validation raises the enforce.h-shaped taxonomy
(core/errors.py) with op-name + got-vs-expected context, while still
subclassing the builtin users naturally catch."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.core.errors import (
    EnforceNotMet, InvalidArgumentError, NotFoundError,
)


def test_reshape_element_count():
    x = paddle.to_tensor(np.zeros((2, 3), "float32"))
    with pytest.raises(InvalidArgumentError, match=r"reshape.*6 elements"):
        paddle.reshape(x, [4, 2])
    with pytest.raises(InvalidArgumentError, match="one dimension"):
        paddle.reshape(x, [-1, -1])
    # valid reshapes still work, including -1 inference
    assert list(paddle.reshape(x, [3, -1]).shape) == [3, 2]


def test_concat_rank_and_axis():
    a = paddle.to_tensor(np.zeros((2, 3), "float32"))
    b = paddle.to_tensor(np.zeros((2,), "float32"))
    with pytest.raises(InvalidArgumentError, match="rank mismatch"):
        paddle.concat([a, b])
    with pytest.raises(InvalidArgumentError, match="axis 5 out of range"):
        paddle.concat([a, a], axis=5)
    with pytest.raises(InvalidArgumentError, match="empty"):
        paddle.concat([])


def test_matmul_contraction_dims():
    a = paddle.to_tensor(np.zeros((2, 3), "float32"))
    b = paddle.to_tensor(np.zeros((4, 5), "float32"))
    with pytest.raises(InvalidArgumentError, match="K=3.*K=4"):
        paddle.matmul(a, b)
    # transpose flags change the contraction dim
    assert list(paddle.matmul(
        a, paddle.to_tensor(np.zeros((5, 3), "float32")),
        transpose_y=True).shape) == [2, 5]


def test_conv2d_channel_group_checks():
    x = paddle.to_tensor(np.zeros((1, 4, 8, 8), "float32"))
    w_bad = paddle.to_tensor(np.zeros((8, 3, 3, 3), "float32"))
    with pytest.raises(InvalidArgumentError,
                       match=r"conv2d.*input channels 4"):
        F.conv2d(x, w_bad)
    with pytest.raises(InvalidArgumentError, match="rank-4"):
        F.conv2d(paddle.to_tensor(np.zeros((4, 8, 8), "float32")), w_bad)


def test_embedding_dtype_and_weight_rank():
    w = paddle.to_tensor(np.zeros((10, 4), "float32"))
    with pytest.raises(InvalidArgumentError, match="integer"):
        F.embedding(paddle.to_tensor(np.zeros((2,), "float32")), w)
    with pytest.raises(InvalidArgumentError, match="2-D"):
        F.embedding(paddle.to_tensor(np.zeros((2,), "int64")),
                    paddle.to_tensor(np.zeros((10,), "float32")))


def test_dataloader_argument_checks():
    from paddle_tpu.io import DataLoader

    class DS:
        def __len__(self):
            return 4

        def __getitem__(self, i):
            return np.zeros(2, "float32")

    with pytest.raises(InvalidArgumentError, match="batch_size"):
        DataLoader(DS(), batch_size=0)
    with pytest.raises(InvalidArgumentError, match="num_workers"):
        DataLoader(DS(), num_workers=-1)


def test_load_missing_artifact_is_not_found():
    with pytest.raises(NotFoundError, match="no artifact"):
        paddle.load("/tmp/definitely-not-a-real-checkpoint.pdparams")
    # NotFoundError doubles as FileNotFoundError for existing handlers
    with pytest.raises(FileNotFoundError):
        paddle.load("/tmp/definitely-not-a-real-checkpoint.pdparams")


def test_taxonomy_is_catchable_as_builtin():
    # the enforce contract: typed AND builtin-compatible
    x = paddle.to_tensor(np.zeros((2, 3), "float32"))
    with pytest.raises(ValueError):
        paddle.reshape(x, [7, 7])
    with pytest.raises(EnforceNotMet):
        paddle.reshape(x, [7, 7])


def test_grid_sample_mode_typed():
    x = paddle.to_tensor(np.zeros((1, 1, 2, 2), "float32"))
    g = paddle.to_tensor(np.zeros((1, 1, 1, 2), "float32"))
    with pytest.raises(InvalidArgumentError, match="grid_sample"):
        F.grid_sample(x, g, mode="bicubic")
