"""Round-3 functional additions: affine_grid/grid_sample, temporal_shift,
linear-chain CRF + viterbi, hsigmoid_loss, and the fluid-spelling aliases
(reference: grid_sampler_op.cc, temporal_shift_op.cc,
linear_chain_crf_op.cc, crf_decoding_op.cc, hierarchical_sigmoid_op.cc)."""
import itertools
import math

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def test_affine_grid_identity_and_grid_sample_roundtrip():
    n, c, h, w = 2, 3, 5, 7
    rng = np.random.RandomState(0)
    x = rng.randn(n, c, h, w).astype("float32")
    theta = np.tile(np.array([[1, 0, 0], [0, 1, 0]], "float32"), (n, 1, 1))
    grid = F.affine_grid(paddle.to_tensor(theta), [n, c, h, w],
                         align_corners=True)
    assert list(grid.shape) == [n, h, w, 2]
    out = F.grid_sample(paddle.to_tensor(x), grid, align_corners=True)
    np.testing.assert_allclose(out.numpy(), x, rtol=1e-5, atol=1e-5)


def test_grid_sample_nearest_and_zeros_padding():
    x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
    # sample far outside: zeros padding must give 0
    grid = np.full((1, 1, 2, 2), 3.0, "float32")
    out = F.grid_sample(paddle.to_tensor(x), paddle.to_tensor(grid),
                        padding_mode="zeros")
    assert (out.numpy() == 0).all()
    out_b = F.grid_sample(paddle.to_tensor(x), paddle.to_tensor(grid),
                          padding_mode="border")
    assert (out_b.numpy() == 15.0).all()  # clamps to the corner
    # nearest at exact centers matches the array
    ys, xs = np.meshgrid(np.linspace(-1, 1, 4), np.linspace(-1, 1, 4),
                         indexing="ij")
    g = np.stack([xs, ys], -1)[None].astype("float32")
    out_n = F.grid_sample(paddle.to_tensor(x), paddle.to_tensor(g),
                          mode="nearest", align_corners=True)
    np.testing.assert_allclose(out_n.numpy()[0, 0], x[0, 0])


def test_grid_sample_reflection_no_align_corners():
    # x[y, x] = 4y + x is linear, so bilinear sampling returns 4*fy + fx
    # exactly.  align_corners=False unnorm: v = ((c+1)*size - 1)/2, so
    # c=1.35 -> v=4.2 which reflects to 2.8 (reference grid_sampler_op.h:
    # min(extra, 2*size-extra) - 0.5 with extra=|v+0.5| mod 2*size), and
    # c=-1.35 -> v=-1.2 which reflects to 0.2.
    x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
    grid = np.array([[[[1.35, 1.35], [-1.35, -1.35]]]], "float32")
    out = F.grid_sample(paddle.to_tensor(x), paddle.to_tensor(grid),
                        padding_mode="reflection", align_corners=False)
    np.testing.assert_allclose(
        out.numpy().reshape(-1), [4 * 2.8 + 2.8, 4 * 0.2 + 0.2],
        rtol=1e-5, atol=1e-5)


def test_grid_sample_grad_flows():
    rng = np.random.RandomState(1)
    x = paddle.to_tensor(rng.randn(1, 2, 4, 4).astype("float32"),
                         stop_gradient=False)
    g = paddle.to_tensor((rng.rand(1, 3, 3, 2).astype("float32") - 0.5),
                         stop_gradient=False)
    F.grid_sample(x, g).sum().backward()
    assert np.abs(x.grad.numpy()).sum() > 0
    assert np.abs(g.grad.numpy()).sum() > 0


def test_temporal_shift():
    nt, c, h, w = 4, 4, 2, 2  # n=2 segments of t=2
    x = np.arange(nt * c * h * w, dtype="float32").reshape(nt, c, h, w)
    out = F.temporal_shift(paddle.to_tensor(x), seg_num=2,
                           shift_ratio=0.25).numpy()
    v = x.reshape(2, 2, c, h, w)
    # first c/4 channels shifted backward: out[:, t, 0] = v[:, t+1, 0]
    np.testing.assert_allclose(out.reshape(2, 2, c, h, w)[:, 0, 0],
                               v[:, 1, 0])
    np.testing.assert_allclose(out.reshape(2, 2, c, h, w)[:, 1, 0], 0.0)
    # next c/4 shifted forward
    np.testing.assert_allclose(out.reshape(2, 2, c, h, w)[:, 1, 1],
                               v[:, 0, 1])
    np.testing.assert_allclose(out.reshape(2, 2, c, h, w)[:, 0, 1], 0.0)
    # rest untouched
    np.testing.assert_allclose(out.reshape(2, 2, c, h, w)[:, :, 2:],
                               v[:, :, 2:])


def _crf_brute(emit, trans, lens):
    """Enumerate all paths: returns (nll per seq, best path per seq)."""
    b, t, n = emit.shape
    start, stop, tr = trans[0], trans[1], trans[2:]
    nlls, paths = [], []
    for i in range(b):
        L = lens[i]
        best_s, best_p = -1e30, None
        logz_terms = []
        for path in itertools.product(range(n), repeat=L):
            s = start[path[0]] + emit[i, 0, path[0]]
            for u in range(1, L):
                s += tr[path[u - 1], path[u]] + emit[i, u, path[u]]
            s += stop[path[-1]]
            logz_terms.append(s)
            if s > best_s:
                best_s, best_p = s, path
        logz = np.log(np.sum(np.exp(np.asarray(logz_terms))))
        paths.append(list(best_p) + [0] * (t - L))
        nlls.append(logz)  # caller subtracts gold score
    return np.asarray(nlls), np.asarray(paths)


def test_linear_chain_crf_matches_bruteforce():
    rng = np.random.RandomState(2)
    b, t, n = 3, 4, 3
    emit = rng.randn(b, t, n).astype("float32")
    trans = rng.randn(n + 2, n).astype("float32") * 0.5
    lens = np.array([4, 3, 2], "int32")
    lab = rng.randint(0, n, (b, t)).astype("int32")

    nll = F.linear_chain_crf(paddle.to_tensor(emit), paddle.to_tensor(lab),
                             paddle.to_tensor(trans),
                             paddle.to_tensor(lens)).numpy()[:, 0]
    logz, _ = _crf_brute(emit.astype(np.float64),
                         trans.astype(np.float64), lens)
    # gold path scores
    gold = []
    for i in range(b):
        L = lens[i]
        s = trans[0, lab[i, 0]] + emit[i, 0, lab[i, 0]]
        for u in range(1, L):
            s += trans[2 + lab[i, u - 1], lab[i, u]] + emit[i, u, lab[i, u]]
        s += trans[1, lab[i, L - 1]]
        gold.append(s)
    want = logz - np.asarray(gold)
    np.testing.assert_allclose(nll, want, rtol=1e-4, atol=1e-4)


def test_crf_decoding_matches_bruteforce():
    rng = np.random.RandomState(3)
    b, t, n = 3, 4, 3
    emit = rng.randn(b, t, n).astype("float32")
    trans = rng.randn(n + 2, n).astype("float32") * 0.5
    lens = np.array([4, 3, 2], "int32")
    got = F.crf_decoding(paddle.to_tensor(emit), paddle.to_tensor(trans),
                         paddle.to_tensor(lens)).numpy()
    _, want = _crf_brute(emit.astype(np.float64),
                         trans.astype(np.float64), lens)
    np.testing.assert_array_equal(got, want)


def test_crf_trains_to_recover_transitions():
    """CRF loss is differentiable end-to-end: fitting emissions+transitions
    on sequences generated by a deterministic tag cycle drives decode
    accuracy to 100%."""
    rng = np.random.RandomState(4)
    b, t, n = 16, 6, 3
    lab = np.stack([(np.arange(t) + s) % n
                    for s in rng.randint(0, n, b)]).astype("int32")
    feats = np.eye(n, dtype="float32")[lab] + \
        rng.randn(b, t, n).astype("float32") * 0.3
    lens = np.full((b,), t, "int32")

    W = paddle.to_tensor(rng.randn(n, n).astype("float32") * 0.1,
                         stop_gradient=False)
    trans = paddle.to_tensor(np.zeros((n + 2, n), "float32"),
                             stop_gradient=False)
    opt = paddle.optimizer.Adam(learning_rate=0.1, parameters=[W, trans])
    for _ in range(60):
        emit = paddle.matmul(paddle.to_tensor(feats), W)
        loss = F.linear_chain_crf(emit, paddle.to_tensor(lab), trans,
                                  paddle.to_tensor(lens)).mean()
        loss.backward(); opt.step(); opt.clear_grad()
    emit = paddle.matmul(paddle.to_tensor(feats), W)
    decoded = F.crf_decoding(emit, trans, paddle.to_tensor(lens)).numpy()
    acc = (decoded == lab).mean()
    assert acc > 0.95, acc


def _np_hsigmoid(x, lab, num_classes, w, b):
    out = []
    for i in range(len(x)):
        c = lab[i] + num_classes
        length = int(math.floor(math.log2(c)))
        total = 0.0
        for j in range(length):
            idx = (c >> (length - j)) - 1
            bit = (c >> (length - 1 - j)) & 1
            pre = float(x[i] @ w[idx]) + (b[idx] if b is not None else 0.0)
            total += math.log1p(math.exp(-abs(pre))) + max(pre, 0) \
                - bit * pre
        out.append([total])
    return np.asarray(out, np.float64)


def test_hsigmoid_loss_matches_numpy():
    rng = np.random.RandomState(5)
    bsz, d, classes = 6, 8, 10
    x = rng.randn(bsz, d).astype("float32")
    lab = rng.randint(0, classes, (bsz,)).astype("int64")
    w = rng.randn(classes - 1, d).astype("float32") * 0.3
    b = rng.randn(classes - 1).astype("float32") * 0.1
    got = F.hsigmoid_loss(paddle.to_tensor(x), paddle.to_tensor(lab),
                          classes, paddle.to_tensor(w),
                          paddle.to_tensor(b)).numpy()
    want = _np_hsigmoid(x, lab, classes, w, b)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_fluid_spelling_aliases():
    rng = np.random.RandomState(6)
    # detection alias routes to vision.ops
    x = rng.randn(1, 2 * 7, 3, 3).astype("float32")
    img = np.array([[96, 96]], "int32")
    boxes, scores = F.yolo_box(paddle.to_tensor(x), paddle.to_tensor(img),
                               anchors=[10, 13, 16, 30], class_num=2,
                               conf_thresh=0.01, downsample_ratio=32)
    assert boxes.shape[1] == 2 * 3 * 3
    # resize alias
    img4 = paddle.to_tensor(rng.randn(1, 1, 4, 4).astype("float32"))
    up = F.resize_bilinear(img4, out_shape=[8, 8])
    assert list(up.shape) == [1, 1, 8, 8]
    # pool2d alias incl. global pooling
    g = F.pool2d(img4, pool_type="avg", global_pooling=True)
    np.testing.assert_allclose(g.numpy().reshape(-1),
                               img4.numpy().mean(axis=(2, 3)).reshape(-1),
                               rtol=1e-5)
    # space_to_depth / shuffle_channel route to their 2.0 homes
    s = F.space_to_depth(paddle.to_tensor(
        rng.randn(1, 2, 4, 4).astype("float32")), 2)
    assert list(s.shape) == [1, 8, 2, 2]
    # soft_relu / smooth_l1 / dice / bpr smoke with correct shapes
    sr = F.soft_relu(img4)
    assert sr.shape == img4.shape
    a = paddle.to_tensor(rng.randn(3, 5).astype("float32"))
    bt = paddle.to_tensor(rng.randn(3, 5).astype("float32"))
    assert list(F.smooth_l1(a, bt).shape) == [3, 1]
    lab = paddle.to_tensor(rng.randint(0, 5, (3,)).astype("int64"))
    assert list(F.bpr_loss(a, lab).shape) == [3, 1]
    probs = paddle.nn.functional.softmax(a)
    d = F.dice_loss(probs, paddle.to_tensor(
        rng.randint(0, 5, (3, 1)).astype("int64")))
    assert d.size == 1
