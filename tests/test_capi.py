"""C inference API (reference: inference/capi/, train/demo/)."""
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle

capi = pytest.importorskip("paddle_tpu.capi")
if not capi.available():  # pragma: no cover
    pytest.skip("capi build unavailable", allow_module_level=True)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def artifact(tmp_path_factory):
    d = tmp_path_factory.mktemp("capi")
    paddle.seed(0)
    m = paddle.nn.Linear(4, 3)
    m.eval()
    prefix = str(d / "model")
    paddle.jit.save(m, prefix,
                    input_spec=[paddle.static.InputSpec([2, 4], "float32")])
    return prefix, m


def test_ctypes_roundtrip(artifact):
    prefix, m = artifact
    p = capi.CPredictor(prefix)
    x = np.random.RandomState(0).randn(2, 4).astype("float32")
    y = p.run(x)
    ref = np.asarray(m(paddle.to_tensor(x)).numpy())
    np.testing.assert_allclose(y, ref, atol=1e-5)
    p.close()


def test_error_surface():
    p = None
    with pytest.raises(RuntimeError, match="PD_CreatePredictor"):
        p = capi.CPredictor("/nonexistent/model")
    assert p is None


def test_standalone_c_binary(artifact, tmp_path):
    """Compile demo/capi_demo.c into a real C binary that embeds the
    interpreter itself (train/demo parity) and run it out-of-process."""
    prefix, m = artifact
    inc, link = capi.embed_flags()
    exe = str(tmp_path / "capi_demo")
    cmd = (["g++", "-O2", os.path.join(REPO, "demo", "capi_demo.c"),
            os.path.join(REPO, "paddle_tpu", "native", "src", "capi.cc"),
            "-o", exe] + inc + link)
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]

    env = dict(os.environ)
    # drop the axon sitecustomize (it force-registers the TPU plugin in
    # every interpreter; the artifact here is a CPU export)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run([exe, prefix], capture_output=True, text=True,
                         timeout=300, env=env)
    assert out.returncode == 0, (out.stdout, out.stderr[-2000:])
    assert "out_shape=2x3 checksum=" in out.stdout
    # checksum must match the in-process forward on the same ramp input
    x = (np.arange(8, dtype=np.float32) * 0.1).reshape(2, 4)
    expect = float(np.asarray(m(paddle.to_tensor(x)).numpy()).sum())
    got = float(out.stdout.strip().split("checksum=")[1])
    assert abs(got - expect) < 1e-4


def test_train_session_python_side(tmp_path):
    """save_train_program + TrainSession: exported StableHLO step trains
    (reference train/demo program-save half)."""
    from paddle_tpu.jit.train_export import save_train_program, TrainSession
    import paddle_tpu.nn.functional as F
    from paddle_tpu import nn
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    opt = paddle.optimizer.Adam(learning_rate=0.05,
                                parameters=model.parameters())
    prefix = str(tmp_path / "trainp")
    save_train_program(model, lambda out, lbl: F.cross_entropy(out, lbl),
                       opt, prefix,
                       input_specs=[((16, 8), "float32"), ((16,), "int64")])
    sess = TrainSession(prefix)
    rng = np.random.RandomState(0)
    x = rng.randn(16, 8).astype("float32")
    y = (x.sum(1) > 0).astype("int64")
    losses = [sess.step(x, y) for _ in range(15)]
    assert losses[-1] < losses[0]
    # trained state is retrievable (the save_persistables analogue)
    sd = sess.state_dict()
    assert any(v.size for v in sd.values())


def test_standalone_c_train_binary(tmp_path):
    """demo/train_demo.c: a C binary trains the exported step end-to-end —
    the reference's standalone demo_trainer.cc tier."""
    from paddle_tpu.jit.train_export import save_train_program
    import paddle_tpu.nn.functional as F
    from paddle_tpu import nn
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    opt = paddle.optimizer.Adam(learning_rate=0.05,
                                parameters=model.parameters())
    prefix = str(tmp_path / "trainp")
    save_train_program(model, lambda out, lbl: F.cross_entropy(out, lbl),
                       opt, prefix,
                       input_specs=[((16, 8), "float32"), ((16,), "int64")])

    inc, link = capi.embed_flags()
    exe = str(tmp_path / "train_demo")
    cmd = (["g++", "-O2", os.path.join(REPO, "demo", "train_demo.c"),
            os.path.join(REPO, "paddle_tpu", "native", "src", "capi.cc"),
            "-o", exe] + inc + link)
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run([exe, prefix], capture_output=True, text=True,
                         timeout=300, env=env)
    assert out.returncode == 0, (out.stdout, out.stderr[-2000:])
    assert "TRAIN_DEMO_OK" in out.stdout
