"""Recommender-workload tests: sharded + host-resident giant embedding
tables (paddle_tpu.embedding).

Reference pattern: the PS sparse-path unittests —
test_dist_lookup_table / test_lookup_table_v2_op sparse grads +
test_adam_op lazy-mode — recast for the mesh/host-table design:
- deduped gather is EXACT (w[ids] bit-identical);
- the mesh row-sharded TrainStep is bit-identical to the single-device
  Embedding(sparse=True) oracle, and only live rows (and their moments)
  are ever touched;
- the async host-table prefetch pipeline is bit-identical to synchronous
  fetch, degrades (not corrupts) under injected prefetch stalls, and
  detects + refetches injected row corruption;
- checkpoints (rows + moments + cursor) resume bit-exact;
- TrainStep(accum_steps>1)+sparse raises a typed error naming the
  offending params, and the documented dense fallback reaches parity.
"""
import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import jit as pjit
from paddle_tpu.core.selected_rows import RowSparseGrad
from paddle_tpu.embedding import (HostEmbeddingTable, HostPrefetchPipeline,
                                  HostTableTrainStep, RecsysPredictor,
                                  ShardedEmbedding, dedup_gather, dedup_ids)
from paddle_tpu.models import DLRM, DLRMCriterion, dlrm_tiny_config
from paddle_tpu.parallel.mesh import create_mesh
from paddle_tpu.utils import faults

pytestmark = pytest.mark.recsys

CFG = dlrm_tiny_config()
B, F = 16, CFG.num_features


def _batch(i, b=B, high=64):
    rng = np.random.RandomState(1000 + i)
    return (rng.randn(b, CFG.dense_dim).astype("float32"),
            rng.randint(0, high, (b, F)).astype("int64"),
            rng.randint(0, 2, (b, 1)).astype("float32"))


# ---------------------------------------------------------------------------
# dedup units
# ---------------------------------------------------------------------------

def test_dedup_ids_matches_numpy_unique():
    rng = np.random.RandomState(3)
    ids = rng.randint(0, 50, (257,)).astype(np.int32)
    uids, inv, nu = jax.jit(dedup_ids, static_argnums=1)(
        jnp.asarray(ids), 50)
    uids, inv, nu = np.asarray(uids), np.asarray(inv), int(nu)
    ref_u, ref_inv = np.unique(ids, return_inverse=True)
    assert nu == len(ref_u)
    np.testing.assert_array_equal(uids[:nu], ref_u)
    assert np.all(uids[nu:] == 50)  # sentinel tail
    # inv maps every lookup to the slot holding its id
    np.testing.assert_array_equal(uids[inv], ids)
    np.testing.assert_array_equal(inv, ref_inv)


def test_dedup_gather_is_exact():
    rng = np.random.RandomState(4)
    w = jnp.asarray(rng.randn(40, 6).astype("float32"))
    ids = jnp.asarray(rng.randint(0, 40, (123,)).astype(np.int32))
    out, uids, inv = dedup_gather(w, ids)
    assert np.array_equal(np.asarray(out), np.asarray(w)[np.asarray(ids)])


# ---------------------------------------------------------------------------
# sharded-device leg: parity with the sparse oracle, live-rows-only updates
# ---------------------------------------------------------------------------

def test_sharded_train_step_bit_identical_to_sparse_oracle():
    """The tier-1 smoke from the issue: DLRM with a tp=8 row-sharded table
    trains bit-identically (losses AND table) to the single-device
    Embedding(sparse=True) oracle, and only live rows / moments move."""
    paddle.seed(0)
    oracle = DLRM(CFG, embedding="sparse")
    init = {k: np.asarray(v._data) for k, v in oracle.state_dict().items()}
    opt1 = paddle.optimizer.Adam(0.01, parameters=oracle.parameters())
    step1 = pjit.TrainStep(oracle, DLRMCriterion(), opt1)

    mesh = create_mesh({"tp": 8})
    paddle.seed(0)
    sharded = DLRM(CFG, embedding="sharded", mesh=mesh)
    sd2 = sharded.state_dict()
    for k, v in init.items():  # deep copy: donation must not alias models
        sd2[k]._set_data(jax.device_put(jnp.asarray(v),
                                        sd2[k]._data.sharding)
                         if k == "table.weight" else jnp.asarray(v))
    assert sd2["table.weight"].row_shard_axis == "tp"
    opt2 = paddle.optimizer.Adam(0.01, parameters=sharded.parameters())
    step2 = pjit.TrainStep(sharded, DLRMCriterion(), opt2)

    w_before = np.asarray(sd2["table.weight"]._data)
    batches = [_batch(i) for i in range(2)]
    paddle.seed(42)
    oracle_losses = [np.asarray(step1(*map(paddle.to_tensor, b))._data)
                     for b in batches]
    paddle.seed(42)
    sharded_losses = [np.asarray(step2(*map(paddle.to_tensor, b))._data)
                      for b in batches]
    for lo, ls in zip(oracle_losses, sharded_losses):
        assert np.array_equal(lo, ls), "loss diverged from the oracle"
    w1 = np.asarray(oracle.state_dict()["table.weight"]._data)
    w2 = np.asarray(sharded.state_dict()["table.weight"]._data)
    assert np.array_equal(w1, w2), "tables diverged"

    # lazy update proof: rows never looked up are BIT-identical, their
    # adam moments still exactly zero
    live = np.unique(np.concatenate(
        [b[1] + CFG.offsets.reshape(1, -1) for b in batches]))
    untouched = np.setdiff1d(np.arange(CFG.total_rows), live)
    w2_after = np.asarray(sd2["table.weight"]._data)
    assert np.array_equal(w_before[untouched], w2_after[untouched])
    assert not np.array_equal(w_before[live], w2_after[live])
    m1 = np.asarray(step2._opt_state["table.weight"]["moment1"])
    assert np.all(m1[untouched] == 0)
    assert np.any(m1[live] != 0)


def test_sharded_embedding_eager_lazy_update_per_shard():
    """Eager tape path: grads are RowSparseGrad and Optimizer.step routes
    the row-sharded weight through the per-shard lazy update."""
    mesh = create_mesh({"tp": 8})
    paddle.seed(1)
    emb = ShardedEmbedding(64, 8, mesh=mesh)
    opt = paddle.optimizer.Adam(0.1, parameters=[emb.weight])
    ids = np.array([3, 3, 9, 20, 63], np.int64)
    out = emb(paddle.to_tensor(ids))
    (out * out).sum().backward()
    assert isinstance(emb.weight.grad, RowSparseGrad)
    w0 = np.asarray(emb.weight._data)
    opt.step()
    w1 = np.asarray(emb.weight._data)
    untouched = np.setdiff1d(np.arange(64), np.unique(ids))
    assert np.array_equal(w0[untouched], w1[untouched])
    assert not np.array_equal(w0[np.unique(ids)], w1[np.unique(ids)])


def test_sharded_embedding_rejects_undivisible_vocab():
    mesh = create_mesh({"tp": 8})
    with pytest.raises(Exception, match="divide evenly"):
        ShardedEmbedding(63, 8, mesh=mesh)


# ---------------------------------------------------------------------------
# host-resident leg: async prefetch parity + fault degradation + resume
# ---------------------------------------------------------------------------

def _run_host(steps=6, async_prefetch=True, save_dir=None, save_at=None,
              start=0):
    paddle.seed(0)
    model = DLRM(CFG, embedding="external")
    opt = paddle.optimizer.Adam(0.01, parameters=model.parameters())
    table = HostEmbeddingTable(CFG.total_rows, CFG.embedding_dim, seed=7)
    step = HostTableTrainStep(model, DLRMCriterion(), opt, table)
    if save_dir is not None and start > 0:
        meta = step.restore_checkpoint(save_dir)
        start = meta["data_cursor"]["batch_index"]
    pipe = HostPrefetchPipeline(table, _batch, steps, optimizer=opt,
                                offsets=CFG.offsets,
                                async_prefetch=async_prefetch, bucket=64,
                                start_index=start)
    losses = []
    while True:
        prep = pipe.next_prepared()
        if prep is None:
            break
        loss, new_slab, new_states = step.run(prep, (B, F))
        pipe.complete(prep, new_slab, new_states)
        losses.append(float(np.asarray(loss._data)))
        if save_dir is not None and save_at is not None \
                and prep.index + 1 == save_at:
            step.save_checkpoint(save_dir, pipeline=pipe)
    pipe.close()
    params = {k: np.asarray(v._data) for k, v in
              model.state_dict().items()}
    return losses, table, params, pipe.metrics()


_CLEAN = {}


def _clean_host_run():
    if not _CLEAN:
        losses, table, params, metrics = _run_host(async_prefetch=False)
        _CLEAN.update(losses=losses, rows=table.rows.copy(),
                      moments={k: v.copy() for k, v in
                               table.opt_slabs.items()},
                      params=params)
    return _CLEAN


def test_host_pipeline_async_bit_identical_to_sync():
    clean = _clean_host_run()
    losses, table, params, metrics = _run_host(async_prefetch=True)
    assert losses == clean["losses"]
    assert np.array_equal(table.rows, clean["rows"])
    for k, v in clean["moments"].items():
        assert np.array_equal(table.opt_slabs[k], v)
    # the whole point: the prefetch actually overlapped
    assert metrics["hits"] >= 1
    assert metrics["peak_device_table_bytes"] > 0
    # the working set on device stays far below the table in host RAM
    assert metrics["peak_device_table_bytes"] < table.nbytes


@pytest.mark.faults
def test_prefetch_stall_fault_degrades_to_synchronous():
    """PDTPU_FAULT_PREFETCH_STALL: the pipeline must degrade to
    synchronous-fetch behavior (consumer waits, hit rate collapses)
    WITHOUT changing any training result."""
    clean = _clean_host_run()
    faults.enable("prefetch_stall", "30")
    try:
        losses, table, _, metrics = _run_host(async_prefetch=True)
    finally:
        faults.reset()
    assert losses == clean["losses"]
    assert np.array_equal(table.rows, clean["rows"])
    assert metrics["misses"] > metrics["hits"]
    assert metrics["wait_seconds"] > 0.05


@pytest.mark.faults
def test_row_corrupt_fault_detected_and_refetched():
    """PDTPU_FAULT_ROW_CORRUPT poisons one prefetched row copy: the
    consume-side verify must detect it, refetch from the host table, and
    training must stay bit-identical to a clean run."""
    clean = _clean_host_run()
    faults.enable("row_corrupt", "3")
    try:
        losses, table, _, metrics = _run_host(async_prefetch=True)
    finally:
        faults.reset()
    assert metrics["corrupt_refetches"] == 1
    assert losses == clean["losses"]
    assert np.array_equal(table.rows, clean["rows"])
    assert np.isfinite(table.rows).all()


def test_host_table_checkpoint_resume_bit_exact():
    """Mid-run checkpoint (rows + moments + cursor) then a cold restart
    from it reproduces the uninterrupted run bit-exactly."""
    clean = _clean_host_run()
    with tempfile.TemporaryDirectory() as ck:
        _run_host(steps=6, save_dir=ck, save_at=3)
        losses, table, params, _ = _run_host(steps=6, save_dir=ck, start=1)
        assert losses == clean["losses"][3:]
        assert np.array_equal(table.rows, clean["rows"])
        for k, v in clean["moments"].items():
            assert np.array_equal(table.opt_slabs[k], v)
        for k, v in clean["params"].items():
            assert np.array_equal(params[k], v)


def test_observability_embedding_section():
    _clean_host_run()  # ensure counters moved at least once
    from paddle_tpu import observability
    rep = observability.report()["embedding"]
    assert rep["rows_gathered"] > 0
    assert rep["rows_unique"] > 0
    assert rep["dedup_ratio"] >= 1.0
    assert rep["host_to_device_bytes"] > 0
    assert "prefetch_wait_seconds" in rep


# ---------------------------------------------------------------------------
# jit restriction: typed error + dense fallback parity
# ---------------------------------------------------------------------------

def test_accum_sparse_typed_error_names_params_and_dense_fallback():
    paddle.seed(0)
    sparse_model = DLRM(CFG, embedding="sparse")
    init = {k: np.asarray(v._data)
            for k, v in sparse_model.state_dict().items()}
    opt = paddle.optimizer.Adam(0.01,
                                parameters=sparse_model.parameters())
    with pytest.raises(NotImplementedError) as e:
        pjit.TrainStep(sparse_model, DLRMCriterion(), opt, accum_steps=2)
    # the typed error names the offending parameters, not just the rule
    assert "accum_steps=2" in str(e.value)
    assert "table.weight" in str(e.value)
    assert "sparse=False" in str(e.value)

    # documented fallback: sparse=False composes with accum_steps>1, and
    # one accumulated step over the split batch matches one sparse step
    # over the full batch (mean loss + averaged grads)
    step_sparse = pjit.TrainStep(sparse_model, DLRMCriterion(), opt)
    dense, ids, label = _batch(0)
    paddle.seed(9)
    step_sparse(paddle.to_tensor(dense), paddle.to_tensor(ids),
                paddle.to_tensor(label))

    paddle.seed(0)
    dense_model = DLRM(CFG, embedding="dense")
    sd = dense_model.state_dict()
    for k, v in init.items():
        sd[k]._set_data(jnp.asarray(v))
    opt2 = paddle.optimizer.Adam(0.01,
                                 parameters=dense_model.parameters())
    step_accum = pjit.TrainStep(dense_model, DLRMCriterion(), opt2,
                                accum_steps=2)
    paddle.seed(9)
    step_accum(paddle.to_tensor(dense), paddle.to_tensor(ids),
               paddle.to_tensor(label))
    w1 = np.asarray(sparse_model.state_dict()["table.weight"]._data)
    w2 = np.asarray(dense_model.state_dict()["table.weight"]._data)
    np.testing.assert_allclose(w1, w2, rtol=2e-6, atol=2e-7)


# ---------------------------------------------------------------------------
# serving-side lookup path
# ---------------------------------------------------------------------------

def test_recsys_predictor_batched_dedup_scoring_parity():
    from paddle_tpu.jit import functional_call, state_arrays
    paddle.seed(0)
    model = DLRM(CFG, embedding="external")
    table = HostEmbeddingTable(CFG.total_rows, CFG.embedding_dim, seed=7)
    import paddle_tpu.inference as infer
    cfg = infer.Config()
    cfg.enable_recsys_serving(model=model, table=table,
                              offsets=CFG.offsets, window_ms=5.0)
    pred = infer.create_predictor(cfg)
    assert isinstance(pred, RecsysPredictor)
    try:
        dense, ids, _ = _batch(0, b=24)
        resps = [pred.submit(dense[k:k + 8], ids[k:k + 8])
                 for k in range(0, 24, 8)]
        got = np.concatenate([r.result(30) for r in resps], axis=0)
        gids = (ids.astype(np.int64)
                + CFG.offsets.reshape(1, -1)).reshape(-1)
        emb = table.rows[gids].reshape(24, F, CFG.embedding_dim)
        ref = functional_call(model, state_arrays(model),
                              jnp.asarray(dense), jnp.asarray(emb),
                              training=False)
        np.testing.assert_allclose(got, np.asarray(ref), rtol=1e-6,
                                   atol=1e-6)
        # requests merged into fewer forwards than submissions
        assert pred.metrics()["batches"] <= len(resps)
    finally:
        pred.close()


def test_recsys_predictor_queue_full_rejects_terminally():
    paddle.seed(0)
    model = DLRM(CFG, embedding="external")
    table = HostEmbeddingTable(CFG.total_rows, CFG.embedding_dim, seed=7)
    pred = RecsysPredictor(model, table, offsets=CFG.offsets,
                           max_queue=1, start=False)
    try:
        dense, ids, _ = _batch(0, b=4)
        ok = pred.submit(dense, ids)
        shed = pred.submit(dense, ids)
        assert not ok.done  # queued, loop not running
        assert shed.done and shed.failed
        assert "shed" in shed.error
        with pytest.raises(RuntimeError, match="shed"):
            shed.result(0.1)
    finally:
        pred.close()


# ---------------------------------------------------------------------------
# probe smoke (slow: subprocess compile-heavy)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_recsys_probe_smoke(cpu8_env):
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(here, "probes", "recsys_probe.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=900, env=cpu8_env,
        cwd=here)
    line = [l for l in proc.stdout.splitlines()
            if l.startswith("RECSYS")]
    assert line, f"no RECSYS line: {(proc.stderr or proc.stdout)[-800:]}"
    import json
    rec = json.loads(line[0][len("RECSYS"):])
    assert not rec.get("failures"), rec["failures"]
    assert rec["sharded_parity_bit_exact"]
    assert rec["resume_bit_exact"]
    assert rec["rows_per_sec"] > 0
