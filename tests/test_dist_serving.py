"""Distributed serving (ISSUE-8): paged KV block pool + tensor-parallel
prefill/decode over the mesh.

Covers: the block allocator (alloc/free/recycle bookkeeping, fragmentation
churn, fault-capped capacity), the scrub-on-recycle proof (a freed block
re-served to a new request provably contains no prior KV on device),
paged-engine stream parity (greedy bit-identical to solo generate, sampled
bit-identical to the fixed-pool engine, compile count at the
len(buckets)+1 bound with ZERO post-warmup compiles in the program
registry), block-table overflow at max_len, paged preempt/restore
round-trips, KV exhaustion as backpressure (PDTPU_FAULT_KV_EXHAUST:
preempt-park-resume and the typed KVPoolExhaustedError terminal), the
paged-attention op (jnp fallback parity + pallas kernel via interpreter),
and the tensor-parallel engine on the 8-virtual-device CPU mesh
(bit-identical streams vs single-device, params/KV shardings asserted)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import models, parallel
from paddle_tpu.core.errors import InvalidArgumentError
from paddle_tpu.nn.layer_base import Layer
from paddle_tpu.nn.layer.common import Embedding
from paddle_tpu.serving import (KVPoolExhaustedError, PagedKVPool,
                                ServingEngine)
from paddle_tpu.utils import faults

pytestmark = pytest.mark.dist_serving


def tiny_gpt():
    cfg = models.GPTConfig(vocab_size=13, hidden_size=16,
                           num_hidden_layers=2, num_attention_heads=2,
                           hidden_dropout_prob=0.0,
                           attention_probs_dropout_prob=0.0,
                           max_position_embeddings=64)
    paddle.seed(7)
    m = models.GPTForPretraining(cfg)
    m.eval()
    return m


def tp_gpt():
    """8 heads / divisible dims so every tp=8 sharding rule engages."""
    cfg = models.GPTConfig(vocab_size=64, hidden_size=32,
                           num_hidden_layers=2, num_attention_heads=8,
                           hidden_dropout_prob=0.0,
                           attention_probs_dropout_prob=0.0,
                           max_position_embeddings=64)
    paddle.seed(7)
    m = models.GPTForPretraining(cfg)
    m.eval()
    return m


def solo(model, prompt, max_new, **kw):
    out, _ = model.generate(paddle.to_tensor(
        np.asarray(prompt, np.int32)[None]), max_new_tokens=max_new, **kw)
    return np.asarray(out.numpy())[0].tolist()


class MarkerModel(Layer):
    """Protocol model whose KV is a (token+1)-valued marker per written
    position: stale-KV leaks are directly visible in the block pool."""

    def __init__(self, vocab=24):
        super().__init__()
        self.emb = Embedding(vocab, vocab)

    def gen_fixed_cache(self, batch_size, max_length, dtype=None):
        import jax.numpy as jnp
        dt = dtype or jnp.float32
        return [(jnp.zeros((batch_size, max_length, 1, 2), dt),
                 jnp.zeros((batch_size, max_length, 1, 2), dt))]

    def forward_fixed(self, input_ids, caches, pos):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.core.tensor import unwrap
        ids = unwrap(input_ids)
        p = unwrap(pos)
        b, s = ids.shape
        logits = unwrap(self.emb(input_ids)).astype(jnp.float32)
        k, v = caches[0]
        chunk = jnp.broadcast_to(
            (ids.astype(k.dtype) + 1)[:, :, None, None], (b, s, 1, 2))
        k = jax.lax.dynamic_update_slice(k, chunk, (0, p, 0, 0))
        v = jax.lax.dynamic_update_slice(v, chunk, (0, p, 0, 0))
        return logits, [(k, v)]


# ---------------------------------------------------------------------------
# block allocator units
# ---------------------------------------------------------------------------

def test_allocator_alloc_free_recycle():
    pool = PagedKVPool(num_blocks=8, block_size=4, pool_len=32)
    assert pool.max_blocks_per_slot == 8
    assert pool.free_blocks() == 8
    assert pool.alloc(0, rows=5)          # 2 blocks
    assert pool.rows_capacity(0) == 8
    assert pool.used_blocks() == 2
    assert pool.ensure(0, rows=8)         # no growth needed
    assert pool.used_blocks() == 2
    assert pool.ensure(0, rows=9)         # third block
    assert pool.used_blocks() == 3
    first_tables = pool.block_ids(0)
    assert len(set(first_tables)) == 3    # distinct blocks
    # table rendering: sentinel tail
    tbl = pool.table_array(0)
    assert tbl.shape == (8,)
    assert list(tbl[:3]) == first_tables
    assert all(t == pool.num_blocks for t in tbl[3:])
    # a second slot cannot steal slot 0's blocks
    assert pool.alloc(1, rows=20)         # 5 blocks: pool now full
    assert set(pool.block_ids(1)).isdisjoint(first_tables)
    assert not pool.ensure(0, rows=13)    # exhausted: False, no change
    assert pool.used_blocks() == 8
    # LIFO recycle: freeing slot 1 re-serves its blocks
    assert pool.free(1) == 5
    assert pool.free_blocks() == 5
    assert pool.ensure(0, rows=13)
    assert pool.free(0) == 4
    assert pool.free(0) == 0              # double free is a no-op
    assert pool.used_blocks() == 0
    with pytest.raises(InvalidArgumentError):
        pool.alloc(0, rows=4) and pool.alloc(0, rows=4)


def test_allocator_fragmentation_churn():
    """Mixed-length Poisson alloc/free churn: tables stay disjoint, the
    books always balance, and everything frees back to a full pool."""
    rng = np.random.RandomState(0)
    pool = PagedKVPool(num_blocks=24, block_size=4, pool_len=64)
    live = {}
    for step in range(400):
        if live and (rng.rand() < 0.45 or len(live) == 12):
            slot = int(rng.choice(sorted(live)))
            pool.free(slot)
            del live[slot]
        else:
            slot = next(i for i in range(100) if i not in live)
            rows = int(rng.poisson(10)) + 1
            if pool.ensure(slot, rows):
                live[slot] = rows
            else:
                pool.free(slot)  # partial-failure path must stay clean
        # invariants
        all_ids = [b for s in live for b in pool.block_ids(s)]
        assert len(all_ids) == len(set(all_ids)), "block double-served"
        assert pool.used_blocks() + pool.free_blocks() == 24
        for s, rows in live.items():
            assert pool.rows_capacity(s) >= min(rows, 64)
    for s in list(live):
        pool.free(s)
    assert pool.free_blocks() == 24 and pool.used_blocks() == 0


@pytest.mark.faults
def test_allocator_fault_cap_is_live():
    pool = PagedKVPool(num_blocks=16, block_size=4, pool_len=32)
    assert pool.capacity() == 16
    faults.enable("kv_exhaust", "3")
    try:
        assert pool.capacity() == 3
        assert pool.free_blocks() == 3
        assert not pool.ensure(0, rows=16)   # 4 blocks > cap
        assert pool.ensure(0, rows=12)       # 3 blocks == cap
        assert pool.free_blocks() == 0
        assert not pool.can_ever_fit(16)
    finally:
        faults.reset()
    assert pool.capacity() == 16 and pool.free_blocks() == 13


# ---------------------------------------------------------------------------
# scrub-on-recycle: device proof
# ---------------------------------------------------------------------------

def test_recycled_block_is_scrubbed():
    """Blocks freed by a long tenant and re-served to a short one must
    contain NOTHING of the prior tenant on device: prefill blocks are
    fully overwritten, decode-entered blocks are zeroed in-program."""
    paddle.seed(3)
    m = MarkerModel()
    m.eval()
    eng = ServingEngine(m, max_slots=1, max_len=32, prefill_buckets=(8,),
                        decode_chunk=2, kv="paged", block_size=4,
                        num_blocks=8)
    r = eng.submit(np.arange(1, 7), max_new_tokens=20)  # rows up to ~25
    eng.run_until_drained(timeout=60)
    assert r.done() and eng.kv_pool.used_blocks() == 0
    k_before = np.asarray(eng._pools[0][0])
    dirty = {b for b in range(8) if np.any(k_before[b] != 0)}
    assert len(dirty) >= 5, "sanity: the long tenant must have left KV"
    # short tenant: 2 prompt blocks + one decode-entered block, recycled
    mark = len(eng.kv_pool.served_log)
    r2 = eng.submit(np.arange(7, 11), max_new_tokens=6)
    eng.run_until_drained(timeout=60)
    assert r2.done()
    served = set(list(eng.kv_pool.served_log)[mark:])
    assert served and served <= dirty, "sanity: re-served blocks were dirty"
    k = np.asarray(eng._pools[0][0])
    # every re-served block may hold ONLY the short tenant's markers
    # (1 + token for its prompt/pads/decodes) and scrub zeros — any other
    # value is the prior tenant's KV leaking through recycling
    allowed = ({0.0, 1.0} | {float(v + 1) for v in [7, 8, 9, 10]}
               | {float(t + 1) for t in r2.tokens()})
    for b in served:
        vals = set(np.unique(k[b]).tolist())
        leaked = vals - allowed
        assert not leaked, f"block {b} leaked prior-tenant KV {leaked}"


@pytest.mark.prefix_cache
def test_cached_block_never_crosses_tenants_and_scrubs_on_recycle():
    """The scrub contract extended to CACHED blocks: a block tenant A's
    prompt left resident in the prefix cache (1) is never mapped into
    tenant B's block table for the SAME prompt without a share policy,
    and (2) once evicted back to the free list and re-served, carries
    nothing of A on device."""
    paddle.seed(3)
    m = MarkerModel()
    m.eval()
    eng = ServingEngine(m, max_slots=1, max_len=32, prefill_buckets=(8,),
                        decode_chunk=2, kv="paged", block_size=4,
                        num_blocks=8, prefix_cache=True)
    prompt_a = np.arange(1, 9)                     # 2 full cached blocks
    ra = eng.submit(prompt_a, max_new_tokens=2, tenant="a")
    eng.run_until_drained(timeout=60)
    assert ra.done() and eng.kv_pool.used_blocks() == 0
    a_chain = eng.prefix_cache.match("a", prompt_a)
    assert len(a_chain) == 2
    # (1) tenant B, SAME prompt: admission must not adopt A's blocks
    rb = eng.submit(prompt_a, max_new_tokens=2, tenant="b")
    eng.run_until_drained(timeout=60)
    assert rb.done()
    b_chain = eng.prefix_cache.match("b", prompt_a)
    assert b_chain and set(b_chain).isdisjoint(a_chain), \
        "tenant B's table reused tenant A's cached blocks"
    # (2) evict everything, then a third tenant recycles the blocks:
    # the in-program scrub must erase the cached markers
    faults.enable("prefix_evict", "0")
    try:
        evicted = set(a_chain) | set(b_chain)
        eng.prefix_cache.enforce_cap()
        assert eng.kv_pool.cached_blocks() == 0
        k_before = np.asarray(eng._pools[0][0])
        assert all(np.any(k_before[b] != 0) for b in evicted), \
            "sanity: evicted blocks still hold markers on device"
        mark = len(eng.kv_pool.served_log)
        prompt_c = np.arange(9, 13)
        rc = eng.submit(prompt_c, max_new_tokens=6, tenant="c")
        eng.run_until_drained(timeout=60)
        assert rc.done()
        served = set(list(eng.kv_pool.served_log)[mark:])
        assert served & evicted, "sanity: recycling must reuse evictees"
        k = np.asarray(eng._pools[0][0])
        allowed = ({0.0, 1.0} | {float(v + 1) for v in prompt_c}
                   | {float(t + 1) for t in rc.tokens()})
        for b in served:
            vals = set(np.unique(k[b]).tolist())
            leaked = vals - allowed
            assert not leaked, \
                f"recycled cached block {b} leaked KV {leaked}"
    finally:
        faults.reset()


# ---------------------------------------------------------------------------
# paged engine: parity, compile bound, overflow, preempt/restore
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def paged_setup():
    m = tiny_gpt()
    eng = ServingEngine(m, max_slots=3, max_len=48, prefill_buckets=(8, 16),
                        decode_chunk=4, kv="paged", block_size=8)
    eng.warmup()
    fixed = ServingEngine(m, max_slots=3, max_len=48,
                          prefill_buckets=(8, 16), decode_chunk=4)
    fixed.warmup()
    return m, eng, fixed


def test_paged_streams_bit_identical_and_zero_post_warmup_compiles(
        paged_setup):
    """Greedy paged streams == solo generate; sampled paged streams ==
    the fixed-pool engine (same per-slot key folds); mixed traffic after
    warmup() adds ZERO compiles — engine counters AND the compiled-
    program registry agree."""
    from paddle_tpu import observability
    from paddle_tpu.core import op as core_op
    model, eng, fixed = paged_setup
    reg = observability.get_program_registry()

    def serving_compiles():
        return {k: v["compiles"] for k, v in reg.snapshot().items()
                if k.startswith("serving_")}

    before = (eng.compile_counts(), serving_compiles(),
              core_op.dispatch_cache_stats()["misses"])
    rng = np.random.RandomState(1)
    greedy_prompts = [rng.randint(0, 13, (n,)) for n in (4, 7, 11, 14)]
    greedy = [eng.submit(p, max_new_tokens=6) for p in greedy_prompts]
    sampled_kw = [
        dict(max_new_tokens=7, decode_strategy="sampling", temperature=0.8,
             top_k=4, seed=9),
        dict(max_new_tokens=5, decode_strategy="sampling", top_p=0.9,
             seed=3),
    ]
    sp = [rng.randint(0, 13, (5,)) for _ in sampled_kw]
    sampled = [eng.submit(p, **kw) for p, kw in zip(sp, sampled_kw)]
    eng.run_until_drained(timeout=240)
    for p, r in zip(greedy_prompts, greedy):
        assert r.tokens(timeout=5) == solo(model, p, 6)
    oracle = [fixed.submit(p, **kw) for p, kw in zip(sp, sampled_kw)]
    fixed.run_until_drained(timeout=240)
    for r, o in zip(sampled, oracle):
        assert r.tokens(timeout=5) == o.tokens(timeout=5)
    after = (eng.compile_counts(), serving_compiles(),
             core_op.dispatch_cache_stats()["misses"])
    assert after == before, "paged traffic must never compile post-warmup"
    cc = eng.compile_counts()
    assert cc["total"] <= cc["bound"] == len(eng.buckets) + 1
    assert eng.warm and eng.metrics()["kv_pool"]["kind"] == "paged"
    assert eng.kv_pool.used_blocks() == 0


def test_paged_slot_reuse_keeps_no_stale_kv(paged_setup):
    model, eng, _ = paged_setup
    rng = np.random.RandomState(5)
    long_p = rng.randint(0, 13, (12,))
    [eng.submit(long_p, max_new_tokens=20) for _ in range(eng.max_slots)]
    eng.run_until_drained(timeout=240)
    short_p = rng.randint(0, 13, (4,))
    rs = [eng.submit(short_p, max_new_tokens=5)
          for _ in range(eng.max_slots)]
    eng.run_until_drained(timeout=240)
    want = solo(model, short_p, 5)
    for r in rs:
        assert r.tokens() == want


def test_block_table_overflow_at_max_len(paged_setup):
    """A request filling max_len exactly runs to the last row without the
    table overflowing; one past it is rejected up front."""
    model, eng, _ = paged_setup
    prompt = np.arange(1, 9)  # plen 8
    r = eng.submit(prompt, max_new_tokens=eng.max_len - 8)  # == max_len
    eng.run_until_drained(timeout=240)
    assert r.tokens() == solo(model, prompt, eng.max_len - 8)
    assert eng.kv_pool.used_blocks() == 0
    with pytest.raises(InvalidArgumentError):
        eng.submit(prompt, max_new_tokens=eng.max_len - 7)
    # a table can never exceed its static width
    assert eng.kv_pool.max_blocks_per_slot * eng.block_size >= eng.max_len


def test_paged_preempt_restore_roundtrip(paged_setup):
    """The dist_serving preempt/restore contract: a paged run preempted
    mid-decode frees its blocks, parks host-side, and resumes into ANY
    slot with the remaining stream bit-identical."""
    model, eng, _ = paged_setup
    prompt = [2, 4, 6]
    r = eng.submit(prompt, max_new_tokens=20)
    eng.step()
    eng.step()
    slot = next(iter(eng._slots))
    used_before = eng.kv_pool.used_blocks()
    paused = eng.preempt_slot(slot)
    assert eng.kv_pool.used_blocks() == 0 < used_before
    assert not r.done()
    assert eng.restore_run(paused)
    eng.run_until_drained(timeout=240)
    assert r.tokens() == solo(model, prompt, 20)
    assert r.request.preempts == 1 and r.request.resumes == 1


# ---------------------------------------------------------------------------
# exhaustion is backpressure, not a crash
# ---------------------------------------------------------------------------

@pytest.mark.faults
def test_kv_exhaustion_preempts_newest_and_stays_correct():
    """An undersized pool: two long runs cannot both grow — the newest
    preempts, parks, resumes as the pool drains, and BOTH streams finish
    bit-identical to solo generate."""
    m = tiny_gpt()
    eng = ServingEngine(m, max_slots=2, max_len=48, prefill_buckets=(8,),
                        decode_chunk=4, kv="paged", block_size=8,
                        num_blocks=7)
    eng.warmup()
    p1, p2 = [1, 2, 3, 4], [5, 6, 7]
    r1 = eng.submit(p1, max_new_tokens=30)
    r2 = eng.submit(p2, max_new_tokens=30)
    eng.run_until_drained(timeout=240)
    assert r1.tokens() == solo(m, p1, 30)
    assert r2.tokens() == solo(m, p2, 30)
    assert eng._oom_preempts >= 1, "pressure must have preempted"
    assert eng.metrics()["kv_pool"]["oom_preempts"] >= 1
    assert eng.kv_pool.used_blocks() == 0


@pytest.mark.faults
def test_kv_exhaust_fault_reaches_typed_terminal():
    """PDTPU_FAULT_KV_EXHAUST=1: the run's next tick can never fit even
    alone -> KVPoolExhaustedError, never a hang; disarming restores full
    service on the same engine."""
    m = tiny_gpt()
    eng = ServingEngine(m, max_slots=2, max_len=48, prefill_buckets=(8,),
                        decode_chunk=4, kv="paged", block_size=8)
    eng.warmup()
    faults.enable("kv_exhaust", "1")
    try:
        r = eng.submit([1, 2, 3], max_new_tokens=30)
        eng.run_until_drained(timeout=60)
    finally:
        faults.reset()
    with pytest.raises(KVPoolExhaustedError):
        r.tokens(timeout=5)
    assert r.finish_reason == "error"
    assert eng.scheduler.free_slot_count() == eng.max_slots
    # the engine keeps serving once the fault clears
    r2 = eng.submit([1, 2, 3], max_new_tokens=6)
    eng.run_until_drained(timeout=60)
    assert r2.tokens() == solo(m, [1, 2, 3], 6)


@pytest.mark.faults
def test_kv_exhaustion_admission_is_backpressure():
    """With the pool capped below two prompts, the second request WAITS
    (block-aware admission gate) and completes after the first drains —
    no error, no hang, FIFO preserved."""
    m = tiny_gpt()
    eng = ServingEngine(m, max_slots=2, max_len=48,
                        prefill_buckets=(8, 16), decode_chunk=4,
                        kv="paged", block_size=8)
    eng.warmup()
    p1 = list(range(1, 10))   # bucket 16 = 2 blocks at admission
    p2 = list(range(2, 11))
    faults.enable("kv_exhaust", "2")  # exactly one such request at a time
    try:
        r1 = eng.submit(p1, max_new_tokens=8)   # rows 16: fits the cap
        r2 = eng.submit(p2, max_new_tokens=8)
        eng.step()
        assert eng.scheduler.occupancy() == 1, "second must wait on blocks"
        assert eng.scheduler.queue_depth() == 1
        eng.run_until_drained(timeout=120)
    finally:
        faults.reset()
    assert r1.tokens() == solo(m, p1, 8)
    assert r2.tokens() == solo(m, p2, 8)


def test_submit_rejects_bucket_that_can_never_admit():
    """The submit-time fit check must use the PREFILL BUCKET the request
    will actually allocate, not just its row budget — otherwise a tiny
    request in a big bucket passes validation but can never pass the
    admission gate (regression: permanent busy-spin)."""
    m = tiny_gpt()
    eng = ServingEngine(m, max_slots=2, max_len=32, prefill_buckets=(16,),
                        kv="paged", block_size=8, num_blocks=1)
    with pytest.raises(InvalidArgumentError, match="KV blocks"):
        eng.submit([1, 2], max_new_tokens=2)  # 4 rows, but bucket 16


@pytest.mark.faults
def test_queued_request_fails_typed_when_fault_cap_blocks_admission():
    """A queued request whose prompt bucket can never fit the LIVE
    (fault-capped) pool must reach the typed KVPoolExhaustedError — not
    wait in the queue forever (regression: run_until_drained spun)."""
    m = tiny_gpt()
    eng = ServingEngine(m, max_slots=2, max_len=48,
                        prefill_buckets=(8, 16), kv="paged", block_size=8)
    eng.warmup()
    faults.enable("kv_exhaust", "1")  # bucket 16 needs 2 blocks: never
    try:
        r = eng.submit(list(range(1, 10)), max_new_tokens=4)  # no deadline
        eng.run_until_drained(timeout=60)
    finally:
        faults.reset()
    with pytest.raises(KVPoolExhaustedError):
        r.tokens(timeout=5)


# ---------------------------------------------------------------------------
# the paged-attention op (jnp fallback + pallas kernel via interpreter)
# ---------------------------------------------------------------------------

def test_paged_attention_op_matches_contiguous_reference():
    import jax.numpy as jnp
    from paddle_tpu.ops import paged_attention as pa
    rng = np.random.RandomState(0)
    nb_pool, bs, h, d = 10, 4, 2, 8
    kpool = jnp.asarray(rng.randn(nb_pool, bs, h, d).astype(np.float32))
    vpool = jnp.asarray(rng.randn(nb_pool, bs, h, d).astype(np.float32))
    # last entry is the allocator's out-of-range SENTINEL: both the jnp
    # fallback (clip) and the pallas kernel (clamped index_map) must
    # accept the engine's real tables
    table = jnp.asarray(np.array([7, 2, 9, nb_pool], np.int32))
    q = jnp.asarray(rng.randn(h, d).astype(np.float32))
    pos = 9  # attends rows 0..9 of the 16-row gathered view

    k = np.asarray(pa.gather_block_rows(kpool, table))
    v = np.asarray(pa.gather_block_rows(vpool, table))
    s = np.einsum("hd,thd->ht", np.asarray(q), k) / np.sqrt(d)
    s[:, pos + 1:] = -np.inf
    p = np.exp(s - s.max(axis=1, keepdims=True))
    p /= p.sum(axis=1, keepdims=True)
    want = np.einsum("ht,thd->hd", p, v)

    got = np.asarray(pa.paged_attention(q, kpool, vpool, table, pos))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    # pallas kernel (interpreter) against the jnp fallback
    pa._INTERPRET = True
    try:
        kern = np.asarray(pa.paged_attention(q, kpool, vpool, table, pos))
    finally:
        pa._INTERPRET = False
    np.testing.assert_allclose(kern, got, rtol=1e-5, atol=1e-5)


def test_scatter_and_scrub_primitives():
    import jax.numpy as jnp
    from paddle_tpu.ops import paged_attention as pa
    pool = jnp.ones((4, 2, 3), jnp.float32)
    # sentinel writes drop; real writes land
    out = pa.scatter_block_rows(
        pool, jnp.asarray([1, 4], jnp.int32), jnp.asarray([1, 0], jnp.int32),
        jnp.asarray(np.full((2, 3), 5.0, np.float32)))
    out = np.asarray(out)
    assert np.all(out[1, 1] == 5.0) and np.all(out[3] == 1.0)
    scr = np.asarray(pa.scrub_blocks(
        jnp.asarray(out), jnp.asarray([2, 9], jnp.int32)))
    assert np.all(scr[2] == 0.0) and np.all(scr[1, 1] == 5.0)


# ---------------------------------------------------------------------------
# tensor parallelism over the 8-virtual-device CPU mesh
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tp_setup():
    m = tp_gpt()
    mesh = parallel.create_mesh({"tp": 8})
    tp_eng = ServingEngine(m, max_slots=2, max_len=32, prefill_buckets=(8,),
                           decode_chunk=4, mesh=mesh)
    tp_eng.warmup()
    sd_eng = ServingEngine(m, max_slots=2, max_len=32,
                           prefill_buckets=(8,), decode_chunk=4)
    sd_eng.warmup()
    return m, mesh, tp_eng, sd_eng


def test_tp_engine_shardings_asserted(tp_setup):
    """No silent full replication: the KV pool is heads-sharded over tp
    and the Megatron param layout is live (col-parallel qkv, row-parallel
    proj, vocab-sharded embedding)."""
    from jax.sharding import PartitionSpec as P
    _, mesh, tp_eng, _ = tp_setup
    kpool = tp_eng._pools[0][0]
    assert not kpool.sharding.is_fully_replicated, "KV pool replicated"
    assert tuple(kpool.sharding.spec)[:3] == (None, None, "tp")
    assert tp_eng._state["gpt.blocks.0.qkv.weight"].sharding.spec == \
        P(None, "tp")
    assert tp_eng._state["gpt.blocks.0.proj.weight"].sharding.spec == \
        P("tp", None)
    assert tp_eng._state["gpt.word_embeddings.weight"].sharding.spec == \
        P("tp", None)
    # norms replicate
    assert tp_eng._state["gpt.ln_f.weight"].sharding.is_fully_replicated
    assert tp_eng.metrics()["mesh"] == {"devices": 8, "tp": 8}


def test_tp_engine_bit_identical_streams(tp_setup):
    """Greedy AND sampled streams from the tp=8 engine match the
    single-device engine token-for-token for the same seeds, with the
    compile count at its bound (programs compiled once under the mesh)."""
    _, _, tp_eng, sd_eng = tp_setup
    rng = np.random.RandomState(3)
    cases = [dict(max_new_tokens=8),
             dict(max_new_tokens=8, decode_strategy="sampling",
                  temperature=0.9, top_k=5, seed=11),
             dict(max_new_tokens=6, decode_strategy="sampling",
                  top_p=0.85, seed=4)]
    for kw in cases:
        p = rng.randint(0, 64, (int(rng.randint(3, 8)),))
        a = tp_eng.submit(p, **kw)
        tp_eng.run_until_drained(timeout=240)
        b = sd_eng.submit(p, **kw)
        sd_eng.run_until_drained(timeout=240)
        assert a.tokens(timeout=5) == b.tokens(timeout=5), kw
    cc = tp_eng.compile_counts()
    assert cc["total"] <= cc["bound"]


def test_tp_fixed_restore_keeps_pool_sharded(tp_setup):
    """Preempt/restore on a mesh engine must re-place the uploaded pool
    with its heads sharding — a default-device array would silently
    de-shard it and retrace the decode program (regression)."""
    m, _, tp_eng, _ = tp_setup
    compiles = tp_eng.compile_counts()["total"]
    r = tp_eng.submit([9, 8, 7], max_new_tokens=10)
    tp_eng.step()
    paused = tp_eng.preempt_slot(next(iter(tp_eng._slots)))
    assert tp_eng.restore_run(paused)
    assert not tp_eng._pools[0][0].sharding.is_fully_replicated, \
        "restore de-sharded the KV pool"
    tp_eng.run_until_drained(timeout=240)
    assert r.tokens() == solo(m, [9, 8, 7], 10)
    assert tp_eng.compile_counts()["total"] == compiles, \
        "restore must not force a retrace"


def test_tp_rejects_fully_replicated_kv_pool():
    """2 heads cannot shard over tp=8: every KV leaf would replicate —
    the engine refuses loudly instead of paying tp x the HBM silently."""
    m = tiny_gpt()  # 2 heads
    mesh = parallel.create_mesh({"tp": 8})
    with pytest.raises(InvalidArgumentError, match="replicated"):
        ServingEngine(m, max_slots=2, max_len=32, prefill_buckets=(8,),
                      mesh=mesh)


def test_tp_rejects_fully_replicated_draft_pool():
    """The guard covers the DRAFT pool too: a draft whose heads cannot
    shard over tp must not silently replicate behind a sharded target."""
    target = tp_gpt()  # 8 heads: shards fine
    dcfg = models.GPTConfig(vocab_size=64, hidden_size=16,
                            num_hidden_layers=1, num_attention_heads=2,
                            hidden_dropout_prob=0.0,
                            attention_probs_dropout_prob=0.0,
                            max_position_embeddings=64)
    draft = models.GPTForPretraining(dcfg)
    draft.eval()
    mesh = parallel.create_mesh({"tp": 8})
    with pytest.raises(InvalidArgumentError, match="draft KV pool"):
        ServingEngine(target, max_slots=2, max_len=32,
                      prefill_buckets=(8,), draft_model=draft,
                      spec_tokens=2, mesh=mesh)


@pytest.mark.gateway
@pytest.mark.faults
def test_gateway_stride_pass_rolls_back_on_block_pressure():
    """try_admit refusing on block pressure is ROUTINE for paged engines;
    the gateway must roll the tenant's stride pass back on the requeue
    path or waiting on capacity eats the tenant's fair share
    (regression)."""
    from paddle_tpu.serving import ServingGateway, TenantConfig
    m = tiny_gpt()
    eng = ServingEngine(m, max_slots=2, max_len=32, prefill_buckets=(8,),
                        kv="paged", block_size=8)
    eng.warmup()
    gw = ServingGateway(eng, tenants={"a": TenantConfig(weight=2.0)})
    faults.enable("kv_exhaust", "0")  # no blocks: admission always waits
    try:
        gw.submit([1, 2, 3], 4, tenant="a")
        for _ in range(5):
            assert not gw._admit_one()
        assert gw._tenants["a"].passes.get(0, 0.0) == 0.0, \
            "failed admissions must not advance the stride pass"
        assert gw.metrics()["lane_depth_lo"] == 1  # still queued
    finally:
        faults.reset()
        gw.close()


@pytest.mark.spec
def test_static_fit_check_matches_runtime_backing():
    """A pool sized exactly for the rows the runtime actually backs
    (plen + max_new - 1) must ACCEPT and serve the request — the static
    check may not add spec headroom the engine never allocates
    (regression: spuriously rejected)."""
    m = tiny_gpt()
    draft = tiny_gpt()
    eng = ServingEngine(m, max_slots=1, max_len=24, prefill_buckets=(8,),
                        draft_model=draft, spec_tokens=3, kv="paged",
                        block_size=8, num_blocks=2)
    eng.warmup()
    r = eng.submit([1, 2, 3, 4], max_new_tokens=13)  # rows 16 == 2 blocks
    eng.run_until_drained(timeout=120)
    assert r.tokens() == solo(m, [1, 2, 3, 4], 13)
    assert eng.kv_pool.used_blocks() == 0


def test_paged_rejects_bad_block_size():
    with pytest.raises(InvalidArgumentError):
        ServingEngine(tiny_gpt(), max_slots=2, max_len=32,
                      prefill_buckets=(8,), kv="paged", block_size=0)


@pytest.mark.slow
def test_tp_paged_engine_matches_single_device():
    """The full tentpole composition: paged KV pool + tensor parallelism,
    bit-identical to the plain single-device engine."""
    m = tp_gpt()
    mesh = parallel.create_mesh({"tp": 8})
    eng = ServingEngine(m, max_slots=4, max_len=32, prefill_buckets=(8,),
                        decode_chunk=4, kv="paged", block_size=8,
                        mesh=mesh)
    eng.warmup()
    sd = ServingEngine(m, max_slots=4, max_len=32, prefill_buckets=(8,),
                       decode_chunk=4)
    sd.warmup()
    assert not eng._pools[0][0].sharding.is_fully_replicated
    rng = np.random.RandomState(9)
    prompts = [rng.randint(0, 64, (n,)) for n in (4, 6, 7)]
    ra = [eng.submit(p, max_new_tokens=10) for p in prompts]
    eng.run_until_drained(timeout=240)
    rb = [sd.submit(p, max_new_tokens=10) for p in prompts]
    sd.run_until_drained(timeout=240)
    for a, b in zip(ra, rb):
        assert a.tokens() == b.tokens()
    assert eng.kv_pool.used_blocks() == 0


@pytest.mark.slow
@pytest.mark.spec
def test_paged_spec_engine_greedy_parity():
    """kv='paged' composes with speculative decoding: the draft pool
    pages through the SAME block tables and greedy streams stay
    bit-identical to solo generate at the unchanged compile bound."""
    m = tiny_gpt()
    draft = tiny_gpt()
    eng = ServingEngine(m, max_slots=2, max_len=48, prefill_buckets=(8,),
                        draft_model=draft, spec_tokens=3, kv="paged",
                        block_size=8)
    eng.warmup()
    p = [3, 1, 4, 1]
    r = eng.submit(p, max_new_tokens=12)
    eng.run_until_drained(timeout=240)
    assert r.tokens() == solo(m, p, 12)
    cc = eng.compile_counts()
    assert cc["total"] <= cc["bound"] == len(eng.buckets) + 1
    assert eng.kv_pool.used_blocks() == 0


# ---------------------------------------------------------------------------
# gateway over a paged engine: warm healthz + preempt/restore unchanged
# ---------------------------------------------------------------------------

@pytest.mark.gateway
def test_gateway_healthz_warm_and_paged_preemption():
    import json
    from paddle_tpu.serving import (PRIORITY_HIGH, ServingGateway,
                                    TenantConfig)
    m = tiny_gpt()
    eng = ServingEngine(m, max_slots=1, max_len=48, prefill_buckets=(8,),
                        decode_chunk=2, kv="paged", block_size=8)
    gw = ServingGateway(eng, tenants={"t": TenantConfig()})
    status, _, body = gw.handle("GET", "/healthz")
    assert status == 200 and json.loads(body)["warm"] is False
    eng.warmup()
    status, _, body = gw.handle("GET", "/healthz")
    assert json.loads(body)["warm"] is True
    # a high-priority arrival preempts the (paged) low run; the victim
    # resumes bit-identical through the same gateway machinery
    lo = gw.submit([1, 2, 3], 16, tenant="t")
    gw._tick()
    gw._tick()  # lo holds the only slot mid-decode
    hi = gw.submit([4, 5], 4, tenant="t", priority=PRIORITY_HIGH)
    gw.run_until_drained(timeout=240)
    assert hi.tokens(timeout=5) == solo(m, [4, 5], 4)
    assert lo.tokens(timeout=5) == solo(m, [1, 2, 3], 16)
    assert lo.request.preempts >= 1 and lo.request.resumes >= 1
    gw.close()
