"""Process-isolated fleet replicas (ISSUE 13): subprocess engine
workers behind the PR-12 router — RPC wire form, out-of-band heartbeat
wedge fencing, SIGKILL + supervised restart under a backoff budget.

Tier-1 keeps every subprocess test to <= 2 workers on the tiny GPT and
arms a hard SIGALRM per-test timeout, so a hung worker (the very
failure mode under test) can never wedge the suite; the full chaos
matrix runs under `slow`.
"""
import json
import os
import signal
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import models
from paddle_tpu.serving import (FleetRouter, ReplicaLostError,
                                RestartBackoff, ServingEngine,
                                WireFormatError, WorkerDiedError)
from paddle_tpu.serving.fleet import ReplicaManager, SubprocessReplica
from paddle_tpu.serving.transfer import (RunTransferError, TRANSFER_VERSION,
                                         check_compatible, encode_run,
                                         engine_config_hash, run_from_bytes,
                                         run_to_bytes)
from paddle_tpu.serving.worker import pack_frame, unpack_frame
from paddle_tpu.utils import faults

pytestmark = pytest.mark.subprocess_fleet

GPT_KW = dict(vocab_size=64, hidden_size=32, num_hidden_layers=2,
              num_attention_heads=2, hidden_dropout_prob=0.0,
              attention_probs_dropout_prob=0.0,
              max_position_embeddings=128)
ENGINE_KW = dict(max_slots=2, max_len=64, prefill_buckets=(8,),
                 decode_chunk=2)


def worker_spec(**engine_overrides):
    ekw = dict(ENGINE_KW, **engine_overrides)
    ekw["prefill_buckets"] = list(ekw["prefill_buckets"])
    return {"model": {"factory": "paddle_tpu.serving.worker:build_gpt",
                      "kwargs": dict(GPT_KW, seed=11)},
            "engine": ekw}


def tiny_model():
    paddle.seed(11)
    m = models.GPTForPretraining(models.GPTConfig(**GPT_KW))
    m.eval()
    return m


def oracle(model, prompt, max_new):
    out, _ = model.generate(paddle.to_tensor(np.asarray(prompt)[None]),
                            max_new_tokens=max_new)
    return np.asarray(out.numpy())[0].tolist()


@pytest.fixture
def hard_timeout():
    """The tier-1 wedge guard: SIGALRM aborts the test outright if a
    worker hang ever leaks past the in-test timeouts."""
    def handler(signum, frame):
        raise TimeoutError("subprocess_fleet hard per-test timeout "
                           "(a worker hang leaked past the in-test "
                           "timeouts)")
    old = signal.signal(signal.SIGALRM, handler)
    signal.alarm(150)
    yield
    signal.alarm(0)
    signal.signal(signal.SIGALRM, old)


@pytest.fixture
def fleet_guard():
    """Closes every registered fleet at teardown — even a failing test
    leaves no orphan worker processes behind."""
    fleets = []
    yield fleets.append
    for fleet in fleets:
        try:
            fleet.close()
        except Exception:
            pass
    faults.reset()


def wait_for(pred, timeout, what):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return
        time.sleep(0.005)
    raise AssertionError(f"timed out after {timeout}s waiting for {what}")


# ---------------------------------------------------------------------------
# pure units: no subprocess spawned
# ---------------------------------------------------------------------------

def test_restart_backoff_schedule_and_budget():
    # deterministic rng: always the jitter midpoint
    bo = RestartBackoff(max_restarts=3, base_delay=0.5, max_delay=10.0,
                        jitter=0.5, rng=lambda a, b: (a + b) / 2)
    # exponential doubling, each with the jitter-midpoint (0.25d) added
    assert bo.delay_for(1) == pytest.approx(0.5 * 1.25)
    assert bo.delay_for(2) == pytest.approx(1.0 * 1.25)
    assert bo.delay_for(3) == pytest.approx(2.0 * 1.25)
    assert bo.delay_for(4) is None  # budget exhausted
    assert bo.delay_for(0) is None
    # max_delay caps the pre-jitter schedule
    bo2 = RestartBackoff(max_restarts=5, base_delay=1.0, max_delay=2.0,
                         jitter=0.0)
    assert [bo2.delay_for(i) for i in range(1, 6)] == [1.0, 2.0, 2.0,
                                                       2.0, 2.0]
    # jitter bounds: delay in [d, (1+jitter)*d]
    bo3 = RestartBackoff(max_restarts=1, base_delay=1.0, jitter=0.5)
    for _ in range(20):
        d = bo3.delay_for(1)
        assert 1.0 <= d <= 1.5


def test_supervisor_schedule_under_injected_clock(monkeypatch):
    """The restart supervisor's schedule is driven by the injected
    clock: nothing spawns before the backoff delay elapses, each failure
    doubles the delay, and the budget's end marks the lineage exhausted
    and stops respawning."""
    now = {"t": 100.0}
    mgr = ReplicaManager(
        heartbeat_timeout_s=None,
        restart_backoff=RestartBackoff(max_restarts=2, base_delay=1.0,
                                       jitter=0.0),
        _clock=lambda: now["t"])
    spawned = []
    monkeypatch.setattr(mgr, "add_worker",
                        lambda spec, lineage=None, **kw:
                        spawned.append(lineage))
    lineage = {"spec": {}, "index": 7, "restarts": 0,
               "client_kw": {}, "exhausted": False}
    mgr._schedule_restart_lineage(lineage)
    assert lineage["restarts"] == 1
    assert mgr._restarts[0]["at"] == pytest.approx(101.0)
    assert not mgr._pump_restarts() and not spawned  # before due time
    now["t"] = 100.5
    assert not mgr._pump_restarts() and not spawned
    now["t"] = 101.0
    assert mgr._pump_restarts() and len(spawned) == 1
    # second failure: doubled delay
    mgr._schedule_restart_lineage(lineage)
    assert lineage["restarts"] == 2
    assert mgr._restarts[0]["at"] == pytest.approx(103.0)
    now["t"] = 103.5
    assert mgr._pump_restarts() and len(spawned) == 2
    # third failure: budget (2) exhausted — typed terminal for the
    # lineage, no further spawns ever
    mgr._schedule_restart_lineage(lineage)
    assert lineage["exhausted"]
    assert mgr.counters()["restarts_exhausted"] == 1
    assert not mgr._restarts
    mgr._schedule_restart_lineage(lineage)  # idempotent once exhausted
    assert not mgr._restarts


def test_wire_frame_roundtrip_and_typed_mismatch():
    frame = pack_frame("submit", {"wid": 3, "temperature": 0.5},
                       {"prompt": np.arange(5, dtype=np.int32)})
    n = int.from_bytes(frame[:8], "big")
    assert n == len(frame) - 8
    verb, h, arrays = unpack_frame(frame[8:])
    assert verb == "submit" and h["wid"] == 3
    assert h["temperature"] == 0.5
    np.testing.assert_array_equal(arrays["prompt"],
                                  np.arange(5, dtype=np.int32))
    # corrupt payload -> typed, never a deep KeyError
    with pytest.raises(WireFormatError):
        unpack_frame(b"not an npz at all")
    # a frame whose wire version disagrees is refused typed
    bad = pack_frame("submit", {})
    verb, h, arrays = unpack_frame(bad[8:])
    h["v"] = 999
    import io
    buf = io.BytesIO()
    np.savez(buf, header=np.frombuffer(json.dumps(h).encode(), np.uint8))
    with pytest.raises(WireFormatError, match="wire version"):
        unpack_frame(buf.getvalue())
    # a headerless npz is typed too
    buf = io.BytesIO()
    np.savez(buf, x=np.zeros(3))
    with pytest.raises(WireFormatError):
        unpack_frame(buf.getvalue())


def test_transfer_wire_carries_version_and_config_hash():
    """ISSUE-13 satellite: the npz wire form embeds the codec version
    and the source engine's config hash, and a target built from a
    different manifest rejects the run TYPED before any row decodes."""
    model = tiny_model()
    eng_a = ServingEngine(model, **ENGINE_KW)
    eng_b_cfg = dict(ENGINE_KW, max_len=48)
    eng_b = ServingEngine(model, **eng_b_cfg)
    ha, hb = engine_config_hash(eng_a), engine_config_hash(eng_b)
    assert ha != hb  # max_len is a transfer-identity axis
    assert ha == engine_config_hash(
        ServingEngine(tiny_model(), **ENGINE_KW))  # deterministic
    # handcraft a snapshot shaped like eng_a's pools
    from paddle_tpu.serving.engine import PreemptedRun
    from paddle_tpu.serving.request import Request, Response
    req = Request(0, np.arange(1, 5, dtype=np.int32), 8)
    rows = [(np.zeros((4,) + tuple(k.shape[2:]), k.dtype),
             np.zeros((4,) + tuple(v.shape[2:]), v.dtype))
            for k, v in eng_a._pools]
    paused = PreemptedRun.from_state(
        req, Response(req), pos=4, produced=1, last_token=1,
        key=np.zeros(2, np.uint32), kv_rows=rows)
    blob = encode_run(paused, engine=eng_a)
    # version + hash ride the npz header across the wire
    rt = run_from_bytes(run_to_bytes(blob))
    assert rt["version"] == TRANSFER_VERSION
    assert rt["manifest"]["config_hash"] == ha
    check_compatible(rt, eng_a)  # self-restore fine
    with pytest.raises(RunTransferError, match="config hash"):
        check_compatible(rt, eng_b)
    # without a source engine the hash is absent: shape checks still run
    anon = run_from_bytes(run_to_bytes(encode_run(paused)))
    assert anon["manifest"]["config_hash"] is None
    check_compatible(anon, eng_a)
    # a foreign codec version is refused at the byte boundary
    old = dict(blob, version=1)
    with pytest.raises(RunTransferError, match="codec version"):
        run_from_bytes(run_to_bytes(old))


def test_config_hash_rides_every_migration_hop():
    """The hash must survive the REAL migration paths — preempt_slot
    stamps it on the PreemptedRun, decode_run keeps it, and a plain
    `encode_run(paused)` (the manager-side hop, no engine in hand)
    still carries it — so a cross-manifest restore is refused typed no
    matter how many decode/re-encode hops the snapshot took."""
    from paddle_tpu.serving.transfer import decode_run
    model = tiny_model()
    eng_a = ServingEngine(model, **ENGINE_KW)
    eng_b = ServingEngine(model, **dict(ENGINE_KW, max_len=48))
    eng_a.warmup()
    resp = eng_a.submit(np.arange(1, 5, dtype=np.int32), 6)
    eng_a.step()
    slot = next(iter(eng_a._slots))
    paused = eng_a.preempt_slot(slot)
    assert paused.source_config_hash == engine_config_hash(eng_a)
    # the manager-side hop: encode WITHOUT an engine in hand
    blob = encode_run(paused)
    assert blob["manifest"]["config_hash"] == engine_config_hash(eng_a)
    with pytest.raises(RunTransferError, match="config hash"):
        check_compatible(blob, eng_b)
    # a decode/re-encode round trip keeps it too (WorkerClient.preempt)
    snap = decode_run(run_from_bytes(run_to_bytes(blob)))
    assert snap.source_config_hash == engine_config_hash(eng_a)
    with pytest.raises(RunTransferError, match="config hash"):
        check_compatible(encode_run(snap), eng_b)
    resp.cancel()
    eng_a.close()


# ---------------------------------------------------------------------------
# tier-1 subprocess smoke: <= 2 workers, tiny GPT, hard timeout
# ---------------------------------------------------------------------------

def test_worker_serves_bit_identical_then_reaps(hard_timeout, fleet_guard):
    """One subprocess worker + one in-process replica: the worker's
    greedy streams are bit-identical to solo generate (same-seed model
    rebuild in the worker process), health/metrics surface the process
    facts, and router close reaps the worker — no orphans, and a second
    SIGKILL of the already-dead pid is a no-op."""
    model = tiny_model()
    fleet = FleetRouter([ServingEngine(model, **ENGINE_KW)],
                        heartbeat_timeout_s=5.0)
    fleet_guard(fleet)
    rid = fleet.add_worker(worker_spec())
    fleet.warmup()
    fleet.start()
    rep = fleet.manager.get(rid)
    assert isinstance(rep, SubprocessReplica) and rep.state == "healthy"
    prompt = np.arange(1, 6, dtype=np.int32)
    want = oracle(model, prompt, 12)
    # route one request explicitly onto the worker
    req, resp = rep.engine.make_request(prompt, 12)
    rep.engine.scheduler.submit(req, resp)
    assert resp.tokens(timeout=60) == want
    # and one through the front door (whichever replica wins)
    assert fleet.submit(prompt, 12).tokens(timeout=60) == want
    snap = rep.snapshot()
    assert snap["kind"] == "subprocess" and snap["process_alive"]
    assert snap["pid"] == rep.engine.pid
    assert snap["heartbeat_age_s"] is not None
    assert rep.engine.post_warmup_compiles() == 0
    health = fleet.health()
    assert health["workers"] == 1
    assert health["all_routable_stale"] is False
    pid = rep.engine.pid
    fleet.close()
    wait_for(lambda: not _pid_alive(pid), 10, "worker reaped on close")
    # double-SIGKILL of the already-dead pid: no-op, never a raise
    rep.engine.kill()
    rep.engine.kill()


def _pid_alive(pid):
    try:
        os.kill(pid, 0)
    except (ProcessLookupError, PermissionError):
        return False
    # still a zombie? reaped children disappear; our waiter reaps
    try:
        done, _ = os.waitpid(pid, os.WNOHANG)
        return done == 0
    except ChildProcessError:
        return True  # alive but not our child


def test_worker_sigkill_failover_resubmit_and_restart(hard_timeout,
                                                      fleet_guard):
    """SIGKILL of the worker mid-decode: the resubmit opt-in stream
    completes bit-identical on the in-process survivor, the non-opt-in
    ends in the typed ReplicaLostError, and the supervisor restarts the
    worker which then serves bit-identical again."""
    model = tiny_model()
    fleet = FleetRouter(
        [ServingEngine(model, **ENGINE_KW)], heartbeat_timeout_s=5.0,
        restart_backoff=RestartBackoff(max_restarts=1, base_delay=0.05,
                                       max_delay=0.2))
    fleet_guard(fleet)
    rid = fleet.add_worker(worker_spec())
    fleet.warmup()
    fleet.start()
    rep = fleet.manager.get(rid)
    prompt = np.arange(1, 6, dtype=np.int32)
    want = oracle(model, prompt, 24)
    # slow ONLY the worker so both streams are still decoding at kill
    rep.engine.set_fault("replica_slow", f"60:1:{rep.lineage['index']}")
    r_opt, o_resp = rep.engine.make_request(prompt, 24, resubmit=True)
    rep.engine.scheduler.submit(r_opt, o_resp)
    r_no, n_resp = rep.engine.make_request(prompt, 24)
    rep.engine.scheduler.submit(r_no, n_resp)
    wait_for(lambda: len(o_resp.tokens_so_far()) >= 1
             and len(n_resp.tokens_so_far()) >= 1, 60,
             "both streams resident on the worker")
    os.kill(rep.engine.pid, signal.SIGKILL)
    # opt-in: seamless bit-identical continuation on the survivor
    assert o_resp.tokens(timeout=60) == want
    # non-opt-in: typed terminal, never a hang
    with pytest.raises(ReplicaLostError):
        n_resp.tokens(timeout=60)
    assert rep.state == "crashed"
    # the supervisor brings a NEW incarnation up (fresh replica id,
    # same worker index/lineage) and it serves bit-identical
    wait_for(lambda: any(
        r.kind == "subprocess" and r.state == "healthy"
        for r in fleet.manager.replicas()), 90, "supervised restart")
    new_rep = next(r for r in fleet.manager.replicas()
                   if r.kind == "subprocess" and r.state == "healthy")
    assert new_rep.id != rid
    assert new_rep.lineage["index"] == rep.lineage["index"]
    assert new_rep.lineage["restarts"] == 1
    req2, resp2 = new_rep.engine.make_request(prompt, 24)
    new_rep.engine.scheduler.submit(req2, resp2)
    assert resp2.tokens(timeout=60) == want
    c = fleet.manager.counters()
    assert c["worker_restarts"] == 1 and c["resubmits"] >= 1


def test_wedge_heartbeat_fence_sigkill_and_budget(hard_timeout,
                                                  fleet_guard):
    """PDTPU_FAULT_REPLICA_WEDGE: the worker's step blocks forever —
    the socket stays up, no call returns — and ONLY the out-of-band
    heartbeat age fences it (the case PDTPU_FAULT_REPLICA_CRASH cannot
    model).  The wedged process is SIGKILLed after the grace period;
    with a zero restart budget the lineage is exhausted and the replica
    removed, with every consumer typed-terminal."""
    model = tiny_model()
    fleet = FleetRouter(
        [ServingEngine(model, **ENGINE_KW)],
        heartbeat_timeout_s=0.8, kill_grace_s=0.2,
        restart_backoff=RestartBackoff(max_restarts=0))
    fleet_guard(fleet)
    rid = fleet.add_worker(worker_spec())
    fleet.warmup()
    fleet.start()
    rep = fleet.manager.get(rid)
    prompt = np.arange(1, 6, dtype=np.int32)
    want = oracle(model, prompt, 24)
    req, resp = rep.engine.make_request(prompt, 24, resubmit=True)
    rep.engine.scheduler.submit(req, resp)
    wait_for(lambda: len(resp.tokens_so_far()) >= 1, 60,
             "stream resident on the worker")
    pid = rep.engine.pid
    rep.engine.set_fault("replica_wedge", f"{rep.lineage['index']}:0")
    t_arm = time.monotonic()
    # the opted-in stream fails over (resubmitted on the survivor,
    # bit-identical) — driven purely by heartbeat age
    assert resp.tokens(timeout=60) == want
    detect_s = time.monotonic() - t_arm
    assert rep.state == "wedged"
    assert "heartbeat age" in rep.fence_reason
    # fencing must land near the threshold, not after some RPC timeout
    assert detect_s < 5.0
    # grace period -> SIGKILL of the wedged pid
    wait_for(lambda: rep.engine.proc.poll() is not None, 15,
             "wedged worker SIGKILLed after grace")
    # zero budget: lineage exhausted, replica removed, no respawn
    wait_for(lambda: fleet.manager.get(rid) is None, 15,
             "exhausted lineage removed")
    assert rep.lineage["exhausted"]
    c = fleet.manager.counters()
    assert c["wedges"] == 1 and c["worker_restarts"] == 0
    assert c["restarts_exhausted"] == 1
    assert not any(r.kind == "subprocess"
                   for r in fleet.manager.replicas())
    _ = pid  # pid reaped via proc.poll above


def test_drain_migrates_runs_across_process_boundary(hard_timeout,
                                                     fleet_guard):
    """Live run migration over the npz wire form, both directions:
    drain the worker -> its resident restores onto the in-process peer
    bit-identical; drain the in-process replica -> its resident
    restores INTO a worker bit-identical."""
    model = tiny_model()
    eng = ServingEngine(model, **ENGINE_KW)
    fleet = FleetRouter([eng], heartbeat_timeout_s=5.0)
    fleet_guard(fleet)
    rid = fleet.add_worker(worker_spec())
    fleet.warmup()
    fleet.start()
    rep = fleet.manager.get(rid)
    inproc_id = next(r.id for r in fleet.manager.replicas()
                     if r.kind == "inproc")
    prompt = np.arange(1, 6, dtype=np.int32)
    want = oracle(model, prompt, 24)
    # out of the worker
    rep.engine.set_fault("replica_slow", f"50:1:{rep.lineage['index']}")
    req, resp = rep.engine.make_request(prompt, 24)
    rep.engine.scheduler.submit(req, resp)
    wait_for(lambda: len(resp.tokens_so_far()) >= 1, 60,
             "stream resident on the worker")
    fleet.drain(rid)
    assert resp.tokens(timeout=60) == want
    assert req.migrations == 1
    wait_for(lambda: fleet.manager.get(rid).state == "closed", 30,
             "drained worker closed")
    # into a fresh worker
    rid2 = fleet.add_worker(worker_spec())
    wait_for(lambda: fleet.manager.get(rid2).state == "healthy", 120,
             "second worker healthy")
    inproc = fleet.manager.get(inproc_id)
    faults.enable("replica_slow", f"50:1:{inproc_id}")
    req2, resp2 = inproc.engine.make_request(prompt, 24)
    inproc.engine.scheduler.submit(req2, resp2)
    wait_for(lambda: len(resp2.tokens_so_far()) >= 1, 60,
             "stream resident in-process")
    fleet.drain(inproc_id)
    faults.disable("replica_slow")
    assert resp2.tokens(timeout=60) == want
    assert req2.migrations == 1
    assert fleet.manager.counters()["migrated"] == 2


def test_no_peer_budget_exhaustion_typed_matrix(hard_timeout, fleet_guard):
    """Worker-only fleet, zero restart budget, SIGKILL: the resident
    resubmit OPT-IN has no survivor to replay on and the locally queued
    request has no peer queue — BOTH must reach the typed
    ReplicaLostError (never a hang), and the exhausted lineage never
    respawns."""
    model = tiny_model()
    fleet = FleetRouter([], heartbeat_timeout_s=5.0,
                        restart_backoff=RestartBackoff(max_restarts=0))
    fleet_guard(fleet)
    rid = fleet.add_worker(worker_spec(max_slots=1))
    fleet.warmup()
    fleet.start()
    rep = fleet.manager.get(rid)
    prompt = np.arange(1, 6, dtype=np.int32)
    rep.engine.set_fault("replica_slow", f"60:1:{rep.lineage['index']}")
    # resident (opted in — but there will be nobody left to resubmit to)
    req_r, resp_r = rep.engine.make_request(prompt, 24, resubmit=True)
    rep.engine.scheduler.submit(req_r, resp_r)
    wait_for(lambda: len(resp_r.tokens_so_far()) >= 1, 60, "resident")
    # queued behind the single slot: never ships (free mirror is 0)
    req_q, resp_q = rep.engine.make_request(prompt, 8)
    rep.engine.scheduler.submit(req_q, resp_q)
    os.kill(rep.engine.pid, signal.SIGKILL)
    with pytest.raises(ReplicaLostError):
        resp_r.tokens(timeout=60)
    with pytest.raises(ReplicaLostError):
        resp_q.tokens(timeout=60)
    wait_for(lambda: fleet.manager.get(rid) is None, 15,
             "exhausted lineage removed")
    c = fleet.manager.counters()
    assert c["restarts_exhausted"] == 1 and c["worker_restarts"] == 0
    assert c["lost"] == 2


# ---------------------------------------------------------------------------
# full chaos matrix (slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_gateway_over_mixed_fleet_with_worker_loss(hard_timeout,
                                                   fleet_guard):
    """ServingGateway fronting a mixed in-process/subprocess fleet:
    traffic flows through the multi-tenant door, a worker SIGKILL mid
    traffic leaves zero hung consumers, and /healthz reports the worker
    block."""
    from paddle_tpu.serving import ServingGateway
    model = tiny_model()
    fleet = FleetRouter(
        [ServingEngine(model, **ENGINE_KW)], heartbeat_timeout_s=5.0,
        restart_backoff=RestartBackoff(max_restarts=1, base_delay=0.05))
    fleet_guard(fleet)
    rid = fleet.add_worker(worker_spec())
    fleet.warmup()
    gw = ServingGateway(fleet)
    fleet_guard(gw)
    gw.start()
    rep = fleet.manager.get(rid)
    prompt = np.arange(1, 6, dtype=np.int32)
    want = oracle(model, prompt, 12)
    resps = [gw.submit(prompt, 12, resubmit=True, session=f"s{i}")
             for i in range(6)]
    time.sleep(0.1)
    os.kill(rep.engine.pid, signal.SIGKILL)
    results = []
    for r in resps:
        assert r._done.wait(timeout=90), "hung consumer"
        if r.error is None:
            results.append(r.tokens() == want)
        else:
            assert isinstance(r.error, ReplicaLostError)
    assert results and all(results)
    status, _, body = gw.handle("GET", "/healthz", b"")
    payload = json.loads(body)
    assert payload["fleet"]["workers"] >= 1
    assert "all_routable_stale" in payload["fleet"]
    gw.close()
