"""Model-averaging + grad-compression family tests (VERDICT r1 missing #5).

Reference behaviors matched: localsgd_optimizer.py (parameter averaging
every k steps), fluid/optimizer.py ModelAverage/EMA apply-restore,
fp16_allreduce_optimizer (compressed grad reduction), DGCMomentumOptimizer.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import parallel
from paddle_tpu import optimizer as opt


class Tiny(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.fc2 = nn.Linear(16, 1)

    def forward(self, x):
        from paddle_tpu.nn import functional as F
        return self.fc2(F.relu(self.fc1(x)))


def _data(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 8).astype("float32")
    y = (x.sum(-1, keepdims=True) > 0).astype("float32")
    return x, y


def _mse(pred, label):
    return ((pred - label) ** 2).mean()


# -- EMA / ModelAverage ------------------------------------------------------

def test_ema_tracks_and_restores():
    paddle.seed(0)
    m = Tiny()
    ema = opt.ExponentialMovingAverage(0.5, parameters=m.parameters())
    o = opt.SGD(0.1, parameters=m.parameters())
    x, y = _data()
    for i in range(5):
        loss = _mse(m(paddle.to_tensor(x)), paddle.to_tensor(y))
        loss.backward()
        o.step()
        o.clear_grad()
        ema.update()
    raw = np.asarray(m.fc1.weight.numpy()).copy()
    with ema.apply():
        avg = np.asarray(m.fc1.weight.numpy()).copy()
        assert not np.allclose(avg, raw)  # shadow lags the raw weights
    np.testing.assert_array_equal(np.asarray(m.fc1.weight.numpy()), raw)


def test_model_average_apply_restore():
    paddle.seed(0)
    m = Tiny()
    ma = opt.ModelAverage(0.5, parameters=m.parameters(),
                          min_average_window=2, max_average_window=4)
    o = opt.SGD(0.1, parameters=m.parameters())
    x, y = _data()
    snaps = []
    for i in range(6):
        loss = _mse(m(paddle.to_tensor(x)), paddle.to_tensor(y))
        loss.backward()
        o.step()
        o.clear_grad()
        ma.step()
        snaps.append(np.asarray(m.fc1.weight.numpy()).copy())
    raw = snaps[-1]
    with ma.apply():
        avg = np.asarray(m.fc1.weight.numpy())
        assert not np.allclose(avg, raw)
        # the window average lies inside the visited range
        lo = np.min(np.stack(snaps), 0) - 1e-6
        hi = np.max(np.stack(snaps), 0) + 1e-6
        assert np.all(avg >= lo) and np.all(avg <= hi)
    np.testing.assert_array_equal(np.asarray(m.fc1.weight.numpy()), raw)


# -- LocalSGD ----------------------------------------------------------------

def _localsgd_run(k_steps, n_steps=4):
    paddle.seed(0)
    m = Tiny()
    o = opt.SGD(0.1, parameters=m.parameters())
    mesh = parallel.create_mesh({"dp": 8})
    step = parallel.LocalSGDTrainStep(m, _mse, o, k_steps=k_steps, mesh=mesh)
    x, y = _data(64)
    losses = [float(step(paddle.to_tensor(x), paddle.to_tensor(y)))
              for _ in range(n_steps)]
    return losses, {k: np.asarray(v.numpy())
                    for k, v in m.state_dict().items()}


def _single_run(n_steps=4):
    paddle.seed(0)
    m = Tiny()
    o = opt.SGD(0.1, parameters=m.parameters())
    x, y = _data(64)
    losses = []
    for _ in range(n_steps):
        loss = _mse(m(paddle.to_tensor(x)), paddle.to_tensor(y))
        loss.backward()
        o.step()
        o.clear_grad()
        losses.append(float(loss))
    return losses, {k: np.asarray(v.numpy())
                    for k, v in m.state_dict().items()}


def test_localsgd_k1_matches_sync_sgd():
    """With SGD and k=1, parameter averaging after each local step equals
    synchronous data parallelism equals single-device full-batch SGD."""
    ll, lp = _localsgd_run(1)
    sl, sp = _single_run()
    np.testing.assert_allclose(ll, sl, rtol=1e-4, atol=1e-5)
    for k in sp:
        np.testing.assert_allclose(lp[k], sp[k], rtol=1e-4, atol=1e-5,
                                   err_msg=k)


def test_localsgd_k3_learns_and_syncs():
    losses, _ = _localsgd_run(3, n_steps=9)
    assert losses[-1] < losses[0]


# -- fp16/bf16 compressed allreduce -----------------------------------------

def test_fp16_allreduce_trains_close_to_exact():
    def run(fp16_ar):
        paddle.seed(0)
        m = Tiny()
        o = opt.SGD(0.1, parameters=m.parameters())
        st = parallel.DistributedStrategy(fp16_allreduce=fp16_ar)
        mesh = parallel.create_mesh({"dp": 8})
        step = parallel.ShardedTrainStep(m, _mse, o, strategy=st, mesh=mesh)
        x, y = _data(64)
        return [float(step(paddle.to_tensor(x), paddle.to_tensor(y)))
                for _ in range(5)]
    a = run(True)
    b = run(False)
    np.testing.assert_allclose(a, b, rtol=2e-2, atol=1e-3)  # bf16 wire noise
    assert a[-1] < a[0]


def test_fp16_allreduce_rejects_sharding():
    st = parallel.DistributedStrategy(fp16_allreduce=True, sharding=True)
    st.sharding_configs.stage = 3
    mesh = parallel.create_mesh({"dp": 8})
    m = Tiny()
    o = opt.SGD(0.1, parameters=m.parameters())
    with pytest.raises(ValueError, match="fp16_allreduce"):
        parallel.ShardedTrainStep(m, _mse, o, strategy=st, mesh=mesh)


# -- DGC ---------------------------------------------------------------------

def test_dgc_momentum_sparsifies_and_converges():
    paddle.seed(0)
    w = paddle.core.tensor.Parameter(
        paddle.to_tensor(np.zeros(64, "float32"))._data, name="w")
    target = np.linspace(-1, 1, 64).astype("float32")
    o = opt.DGCMomentum(0.3, momentum=0.9, parameters=[w], sparsity=0.75)
    deltas = []
    prev = np.asarray(w.numpy()).copy()
    for _ in range(60):
        loss = ((w - paddle.to_tensor(target)) ** 2).sum()
        loss.backward()
        o.step()
        o.clear_grad()
        cur = np.asarray(w.numpy())
        deltas.append((cur != prev).mean())
        prev = cur.copy()
    # sparsified: most steps touch ~25% of coordinates
    assert np.median(deltas) <= 0.3
    # error feedback: still converges
    assert np.abs(prev - target).max() < 0.1


def test_dgc_rampup_is_dense():
    paddle.seed(0)
    w = paddle.core.tensor.Parameter(
        paddle.to_tensor(np.zeros(64, "float32"))._data, name="w")
    o = opt.DGCMomentum(0.1, parameters=[w], sparsity=0.9,
                        rampup_begin_step=100)
    loss = ((w - 1.0) ** 2).sum()
    loss.backward()
    o.step()
    # within rampup every coordinate moves (dense momentum)
    assert np.all(np.asarray(w.numpy()) != 0)


def test_dgc_rampup_equals_plain_momentum():
    """During ramp-up DGC must be plain Momentum (velocity persists)."""
    def run(cls, **kw):
        paddle.seed(0)
        w = paddle.core.tensor.Parameter(
            paddle.to_tensor(np.zeros(16, "float32"))._data, name="w")
        o = cls(0.1, momentum=0.9, parameters=[w], **kw)
        for _ in range(5):
            ((w - 1.0) ** 2).sum().backward()
            o.step()
            o.clear_grad()
        return np.asarray(w.numpy())
    dgc = run(opt.DGCMomentum, sparsity=0.9, rampup_begin_step=100)
    mom = run(opt.Momentum)
    np.testing.assert_allclose(dgc, mom, rtol=1e-6)


def test_localsgd_checkpoint_roundtrip(tmp_path):
    paddle.seed(0)
    m = Tiny()
    o = opt.SGD(0.1, parameters=m.parameters())
    mesh = parallel.create_mesh({"dp": 8})
    step = parallel.LocalSGDTrainStep(m, _mse, o, k_steps=2, mesh=mesh)
    x, y = _data(64)
    for _ in range(3):
        step(paddle.to_tensor(x), paddle.to_tensor(y))
    step.save_checkpoint(str(tmp_path), step=3)
    stacked_before = {k: np.asarray(v) for k, v in step._stacked.items()}

    paddle.seed(0)
    m2 = Tiny()
    o2 = opt.SGD(0.1, parameters=m2.parameters())
    step2 = parallel.LocalSGDTrainStep(m2, _mse, o2, k_steps=2, mesh=mesh)
    meta = step2.restore_checkpoint(str(tmp_path))
    assert meta["step"] == 3
    for k, v in step2._stacked.items():
        np.testing.assert_array_equal(np.asarray(v), stacked_before[k])
    # resumed trajectory continues
    l1 = float(step(paddle.to_tensor(x), paddle.to_tensor(y)))
    l2 = float(step2(paddle.to_tensor(x), paddle.to_tensor(y)))
    assert abs(l1 - l2) < 1e-6


def test_fleet_localsgd_rejects_conflicting_flags():
    from paddle_tpu.distributed import fleet
    st = parallel.DistributedStrategy(localsgd=True, sharding=True)
    fleet.init(strategy=st)
    m = Tiny()
    o = opt.SGD(0.1, parameters=m.parameters())
    with pytest.raises(ValueError, match="localsgd"):
        fleet.distributed_train_step(m, _mse, o, strategy=st)


def test_dgc_quantile_selection_tracks_exact_topk():
    """Pins how far DGCMomentum's quantile-threshold masking deviates from
    TRUE top-k (VERDICT r2 weak #8) — by running the REAL update_one and
    reading which entries it actually applied/cleared."""
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    for size, sparsity in ((100_000, 0.999), (50_000, 0.99)):
        opt = paddle.optimizer.DGCMomentum(learning_rate=1.0,
                                           sparsity=sparsity,
                                           rampup_begin_step=0)
        p = jnp.zeros((size,), jnp.float32)
        g = jnp.asarray(rng.randn(size).astype("float32"))
        state = opt.init_state(p)
        new_p, new_state = opt.update_one(p, g, state, jnp.float32(1.0),
                                          jnp.int32(5))
        applied = np.asarray(new_p) != 0  # velocity == g on the first step
        selected = int(applied.sum())
        k = int(round(size * (1 - sparsity)))
        # selection budget stays close to exact top-k count
        assert abs(selected - k) <= max(2, int(0.3 * k)), (selected, k)
        # the applied set IS the exact top-`selected` by |g|
        exact = set(np.argsort(-np.abs(np.asarray(g)))[:selected])
        assert set(np.nonzero(applied)[0]) == exact
        # error feedback: applied velocity cleared, the rest kept
        vel = np.asarray(new_state["velocity"])
        assert (vel[applied] == 0).all()
        kept = ~applied
        np.testing.assert_allclose(vel[kept], np.asarray(g)[kept])
