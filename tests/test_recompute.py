"""jit.recompute_policy — activation recompute under jit (ISSUE 10).

Covers: policy spec forms and validation, trace-time-only wrapping (eager
untouched), forward/grad parity on tagged ResNet-18 stages at f32 (the
semantics gate — recompute must change liveness, never math), measured
peak-live-bytes reduction on the bf16 tower via the
observability.programs estimator, BatchNorm running-stat updates
re-exported through the checkpoint boundary, TrainStep.warmup() still
zero-compile with recompute tagged, and serving (GPT blocks ship
pre-tagged) still warm + stream-identical with the policy active.
"""
import contextlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn
from paddle_tpu.core import recompute as rc
from paddle_tpu.jit import (TrainStep, functional_call, layout_policy,
                            recompute_policy, state_arrays)
from paddle_tpu.observability.programs import peak_live_bytes
from paddle_tpu.vision import models as vmodels

pytestmark = pytest.mark.hbm


@pytest.fixture(autouse=True)
def _clear_policy():
    yield
    recompute_policy(None)


def _resnet18(seed=0):
    paddle.seed(seed)
    return vmodels.resnet18(num_classes=0, with_pool=False)


def _tower(model, amp):
    from paddle_tpu import amp as amp_mod

    def f(state, x):
        def run():
            out = functional_call(model, state, x, training=True)
            return jnp.sum(out.astype(jnp.float32) ** 2)
        if not amp:
            return run()
        with amp_mod.auto_cast(level="O2", dtype="bfloat16"):
            return run()

    def g(state, x):
        return jax.value_and_grad(f)(state, x)
    return g


# ---------------------------------------------------------------------------
# policy plumbing
# ---------------------------------------------------------------------------

def test_policy_spec_forms():
    from paddle_tpu.vision.models.resnet import BasicBlock
    blk = vmodels.resnet18().layer1[0]
    lin = nn.Linear(4, 4)
    recompute_policy("stages")
    assert rc._matches(blk)       # blocks ship pre-tagged
    assert not rc._matches(lin)
    recompute_policy(BasicBlock)
    assert rc._matches(blk) and not rc._matches(lin)
    recompute_policy((BasicBlock, nn.Linear))
    assert rc._matches(lin)
    recompute_policy({"BasicBlock"})
    assert rc._matches(blk) and not rc._matches(lin)
    recompute_policy(lambda l: isinstance(l, nn.Linear))
    assert rc._matches(lin) and not rc._matches(blk)
    recompute_policy(None)
    assert rc.policy() is None


def test_unknown_checkpoint_policy_rejected_eagerly():
    with pytest.raises(ValueError, match="unknown checkpoint policy"):
        recompute_policy("stages", policy="definitely_not_a_policy")


def test_policy_context_manager_restores():
    assert rc.policy() is None
    with recompute_policy("stages"):
        assert rc.policy() is not None
        with recompute_policy(None):
            assert rc.policy() is None
        assert rc.policy() is not None
    assert rc.policy() is None


def test_eager_execution_never_wrapped():
    """Eager calls (concrete arrays, tape available) bypass the wrap: the
    policy is a compiled-step concept."""
    m = _resnet18()
    m.eval()
    x = paddle.to_tensor(np.random.RandomState(0).randn(
        1, 3, 32, 32).astype("float32"))
    base = m(x).numpy()
    with recompute_policy("stages", policy="nothing_saveable"):
        out = m(x).numpy()
    np.testing.assert_array_equal(out, base)


# ---------------------------------------------------------------------------
# parity + measured liveness
# ---------------------------------------------------------------------------

def _loss_grads(model, x, remat, amp=False):
    g = _tower(model, amp)
    state = state_arrays(model)
    ctx = (recompute_policy("stages", policy="nothing_saveable")
           if remat else contextlib.nullcontext())
    with ctx, layout_policy("NHWC"):
        loss, grads = jax.jit(g)(state, x)
    return float(loss), grads


def test_recompute_forward_grad_parity_f32():
    model = _resnet18()
    x = jnp.asarray(np.random.RandomState(0).randn(2, 3, 64, 64),
                    jnp.float32)
    l0, g0 = _loss_grads(model, x, remat=False)
    l1, g1 = _loss_grads(model, x, remat=True)
    assert abs(l0 - l1) / max(abs(l0), 1e-12) < 1e-5
    num = den = 0.0
    for k in g0:
        a = np.asarray(g0[k], np.float64)
        b = np.asarray(g1[k], np.float64)
        num += float(np.sum((a - b) ** 2))
        den += float(np.sum(a ** 2))
    assert (num / max(den, 1e-30)) ** 0.5 < 1e-4


def test_recompute_reduces_peak_live_bf16_tower():
    """The measured contract (not asserted by construction): checkpointing
    ResNet-50 bottleneck blocks under nothing_saveable lowers estimated
    peak live bytes of the fwd+bwd bf16 tower.  (BasicBlock towers at toy
    shapes measure WORSE — the fused ops already recompute their own
    backwards, so the base leg is light and the remat call-site io
    dominates; the knob is opt-in for exactly this reason.  The full-size
    r50-b64-224 leg lives in probes/hbm_probe.py: ratio ~0.50.)"""
    paddle.seed(0)
    model = vmodels.resnet50(num_classes=0, with_pool=False)
    x = jnp.asarray(np.random.RandomState(0).randn(16, 3, 112, 112),
                    jnp.float32)
    state = state_arrays(model)

    def peak(remat):
        ctx = (recompute_policy("stages", policy="nothing_saveable")
               if remat else contextlib.nullcontext())
        with ctx, layout_policy("NHWC"):
            tr = jax.jit(_tower(model, amp=True)).trace(state, x)
        return int(peak_live_bytes(tr.jaxpr))

    base, remat = peak(False), peak(True)
    assert remat < 0.85 * base, (base, remat)


def test_bn_running_stats_cross_checkpoint_boundary():
    """Buffer updates recorded inside a wrapped subtree re-export through
    the checkpoint as explicit outputs: a compiled TrainStep under the
    policy updates running stats exactly like the unwrapped step."""
    def run(remat):
        paddle.seed(0)
        model = vmodels.resnet18(num_classes=10)
        opt = paddle.optimizer.Momentum(learning_rate=0.05, momentum=0.9,
                                        parameters=model.parameters())
        step = TrainStep(model, lambda lo, la: F.cross_entropy(lo, la),
                         opt)
        rng = np.random.RandomState(3)
        x = paddle.to_tensor(rng.randn(4, 3, 32, 32).astype("float32"))
        y = paddle.to_tensor(rng.randint(0, 10, (4,)).astype("int64"))
        ctx = (recompute_policy("stages") if remat
               else contextlib.nullcontext())
        with ctx:
            loss = float(step(x, y))
        return loss, model

    l0, m0 = run(False)
    l1, m1 = run(True)
    assert abs(l0 - l1) / max(abs(l0), 1e-12) < 1e-5
    for name in ("bn1", "layer1.0.bn1", "layer2.0.bn2"):
        sub0 = m0
        sub1 = m1
        for part in name.split("."):
            sub0 = sub0[int(part)] if part.isdigit() else getattr(sub0, part)
            sub1 = sub1[int(part)] if part.isdigit() else getattr(sub1, part)
        np.testing.assert_allclose(np.asarray(sub1._mean._data),
                                   np.asarray(sub0._mean._data),
                                   rtol=1e-5, atol=1e-6)
        # the unwrapped leg left its stats moved off init too (the update
        # actually happened)
        assert float(np.abs(np.asarray(sub0._variance._data) - 1.0).max()) \
            > 1e-6


def test_fused_ops_fall_back_to_reference_inside_checkpoint():
    """custom_vjp residuals are opaque to jax.checkpoint (saved regardless
    of policy), so the fused BN entries must route to their plain
    differentiable references inside a wrapped subtree."""
    from paddle_tpu.ops import fused_bn_act as K
    assert not rc.inside_checkpoint()
    seen = []
    orig = K.bn_act_reference

    def spy(*a, **kw):
        seen.append(rc.inside_checkpoint())
        return orig(*a, **kw)

    K.bn_act_reference = spy
    try:
        model = _resnet18()
        state = state_arrays(model)
        x = jnp.asarray(np.random.RandomState(0).randn(1, 3, 32, 32),
                        jnp.float32)
        with recompute_policy("stages"):
            jax.jit(_tower(model, amp=False)).trace(state, x)
    finally:
        K.bn_act_reference = orig
    # block BNs hit the reference INSIDE the checkpoint; the stem (not a
    # tagged stage) still runs outside it (custom_vjp recompute wrappers)
    assert True in seen and False in seen


# ---------------------------------------------------------------------------
# warmup / zero-compile contracts with recompute tagged
# ---------------------------------------------------------------------------

def test_trainstep_warmup_zero_compile_with_recompute():
    from paddle_tpu.observability import get_program_registry
    paddle.seed(0)
    model = vmodels.resnet18(num_classes=10)
    opt = paddle.optimizer.Momentum(learning_rate=0.05,
                                    parameters=model.parameters())
    step = TrainStep(model, lambda lo, la: F.cross_entropy(lo, la), opt)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(2, 3, 32, 32).astype("float32"))
    y = paddle.to_tensor(rng.randint(0, 10, (2,)).astype("int64"))
    with recompute_policy("stages"):
        rep = step.warmup(x, y)
        before = _train_step_compiles(get_program_registry(), model)
        loss = float(step(x, y))
        after = _train_step_compiles(get_program_registry(), model)
    assert np.isfinite(loss)
    assert rep["seconds"] >= 0
    assert after == before  # the real step reused the warm program


def _train_step_compiles(reg, model):
    rec = reg.get(f"train_step:{type(model).__name__}")
    return rec["compiles"] if rec else 0


@pytest.mark.slow
def test_serving_warmup_and_streams_with_recompute_tagged():
    """GPT blocks ship pre-tagged: an active recompute policy must not
    change served tokens or break the zero-post-warmup-compiles
    contract (forward-only checkpoint is a no-op for decode)."""
    from paddle_tpu import models
    from paddle_tpu.serving import ServingEngine

    def tiny():
        cfg = models.GPTConfig(vocab_size=13, hidden_size=16,
                               num_hidden_layers=2, num_attention_heads=2,
                               hidden_dropout_prob=0.0,
                               attention_probs_dropout_prob=0.0,
                               max_position_embeddings=64)
        paddle.seed(7)
        m = models.GPTForPretraining(cfg)
        m.eval()
        return m

    def serve(policy):
        ctx = (recompute_policy("stages") if policy
               else contextlib.nullcontext())
        with ctx:
            eng = ServingEngine(tiny(), max_slots=2, max_len=32,
                                prefill_buckets=(8,), decode_chunk=2)
            eng.warmup()
            r = eng.submit(np.arange(5) % 13, max_new_tokens=6)
            eng.run_until_drained(timeout=240)
            toks = r.tokens(timeout=5)
            assert eng.post_warmup_compiles() == 0
        return toks

    assert serve(True) == serve(False)


# ---------------------------------------------------------------------------
# peak_live_bytes estimator basics
# ---------------------------------------------------------------------------

def test_peak_live_estimator_orders_liveness():
    """A program that keeps N big tensors live simultaneously must
    estimate higher than one that consumes each immediately."""
    def fanout(x):
        # all four scaled copies are alive at the final sum
        a, b, c, d = x * 1.1, x * 1.2, x * 1.3, x * 1.4
        return jnp.stack([a, b, c, d]).sum()

    def chain(x):
        for _ in range(4):
            x = x * 1.1
        return x.sum()

    x = jnp.zeros((256, 256), jnp.float32)
    hi = peak_live_bytes(jax.jit(fanout).trace(x).jaxpr)
    lo = peak_live_bytes(jax.jit(chain).trace(x).jaxpr)
    assert hi > lo


def test_peak_live_estimator_reads_through_converts():
    """An f32 upcast of a bf16 buffer reads through to its source: the
    estimate must not double-charge the convert even when the f32 view is
    used far apart (XLA duplicates converts into consumer fusions)."""
    def f(x):
        xf = x.astype(jnp.float32)       # multi-use, long-span
        s = jnp.sum(xf)
        big = jnp.tanh(xf)               # second use, later
        return s + jnp.sum(big)

    x = jnp.zeros((512, 512), jnp.bfloat16)
    est = peak_live_bytes(jax.jit(f).trace(x).jaxpr)
    src = x.size * 2          # 0.5 MB bf16 source
    f32 = x.size * 4          # 1 MB per materialized f32 tensor
    assert est >= src         # the source buffer itself is charged
    # source + one f32 (tanh output) + slack; a charged f32 copy of x
    # would add a second full f32
    assert est < src + f32 + f32 // 2, est
