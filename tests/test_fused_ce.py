"""Fused tied-head softmax-CE (ops/fused_ce.py): numerical parity with
the materialized logits+cross_entropy path, fwd and bwd, plus the
GPTForPretraining(labels=...) wiring."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import models
from paddle_tpu.ops.fused_ce import fused_linear_cross_entropy


def test_fused_ce_matches_reference_fwd_bwd():
    rng = np.random.RandomState(0)
    t, h, v = 12, 8, 30
    hv = rng.randn(2, 6, h).astype("float32")
    wv = (rng.randn(v, h) * 0.2).astype("float32")
    lab = rng.randint(0, v, (2, 6)).astype("int64")

    ht = paddle.to_tensor(hv, stop_gradient=False)
    wt = paddle.to_tensor(wv, stop_gradient=False)
    fused = fused_linear_cross_entropy(ht, wt, paddle.to_tensor(lab),
                                       chunk_size=4)
    assert list(fused.shape) == [2, 6]
    fused.mean().backward()
    gh_f, gw_f = ht.grad.numpy(), wt.grad.numpy()

    ht2 = paddle.to_tensor(hv, stop_gradient=False)
    wt2 = paddle.to_tensor(wv, stop_gradient=False)
    logits = paddle.matmul(ht2, wt2, transpose_y=True)
    ref = F.cross_entropy(logits.reshape([-1, v]),
                          paddle.to_tensor(lab.reshape(-1)),
                          reduction="none")
    np.testing.assert_allclose(fused.numpy().reshape(-1), ref.numpy(),
                               rtol=2e-2, atol=2e-2)  # bf16 MXU dots
    ref.mean().backward()
    np.testing.assert_allclose(gh_f, ht2.grad.numpy(), rtol=5e-2, atol=2e-2)
    np.testing.assert_allclose(gw_f, wt2.grad.numpy(), rtol=5e-2, atol=2e-2)


def test_fused_ce_bias_and_ignore_index():
    """BERT-head form: decoder bias + ignore_index=-100 masking (r5: the
    fused CE is offered to the BERT MLM head too)."""
    rng = np.random.RandomState(1)
    t, h, v = 12, 8, 30
    hv = rng.randn(t, h).astype("float32")
    wv = (rng.randn(v, h) * 0.2).astype("float32")
    bv = (rng.randn(v) * 0.1).astype("float32")
    lab = rng.randint(0, v, (t,)).astype("int64")
    lab[::3] = -100  # ignored positions

    ht = paddle.to_tensor(hv, stop_gradient=False)
    wt = paddle.to_tensor(wv, stop_gradient=False)
    bt = paddle.to_tensor(bv, stop_gradient=False)
    fused = fused_linear_cross_entropy(
        ht, wt, paddle.to_tensor(lab), chunk_size=4, bias=bt,
        ignore_index=-100)
    assert np.all(fused.numpy()[::3] == 0.0)
    n_valid = float((lab != -100).sum())
    (fused.sum() / n_valid).backward()

    # INDEPENDENT numpy reference (float64, closed-form grads) — NOT
    # F.cross_entropy, whose r5 fast path shares authorship (and its
    # masking pattern) with the fused op under test
    lg = hv.astype(np.float64) @ wv.astype(np.float64).T + bv
    p = np.exp(lg - lg.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    valid = lab != -100
    safe = np.where(valid, lab, 0)
    per_tok = np.where(
        valid, -np.log(p[np.arange(t), safe]), 0.0)
    want_mean = per_tok.sum() / valid.sum()
    np.testing.assert_allclose(fused.numpy(), per_tok, rtol=2e-2,
                               atol=2e-2)
    np.testing.assert_allclose(float(fused.sum() / n_valid), want_mean,
                               rtol=2e-2, atol=2e-2)
    d_logits = p.copy()
    d_logits[np.arange(t), safe] -= 1.0
    d_logits *= valid[:, None] / valid.sum()
    np.testing.assert_allclose(ht.grad.numpy(),
                               d_logits @ wv.astype(np.float64),
                               rtol=5e-2, atol=2e-2)
    np.testing.assert_allclose(wt.grad.numpy(),
                               d_logits.T @ hv.astype(np.float64),
                               rtol=5e-2, atol=2e-2)
    np.testing.assert_allclose(bt.grad.numpy(), d_logits.sum(0),
                               rtol=5e-2, atol=2e-2)


@pytest.mark.slow
def test_gpt_forward_labels_path_trains():
    paddle.seed(0)
    cfg = models.GPTConfig(vocab_size=64, hidden_size=32,
                           num_hidden_layers=2, num_attention_heads=2,
                           max_position_embeddings=16,
                           hidden_dropout_prob=0.0,
                           attention_probs_dropout_prob=0.0)
    m = models.GPTForPretraining(cfg)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 64, (2, 16)).astype("int64"))
    # labels path == logits path (same weights, no dropout)
    per_tok = m(ids, labels=ids)
    logits = m(ids)
    ref = F.cross_entropy(logits.reshape([-1, 64]), ids.reshape([-1]),
                          reduction="none").numpy()
    np.testing.assert_allclose(per_tok.numpy().reshape(-1), ref,
                               rtol=2e-2, atol=2e-2)
    # and it trains (tied weight gets BOTH the embedding and the CE grads)
    opt = paddle.optimizer.Adam(1e-2, parameters=m.parameters())
    losses = []
    for _ in range(5):
        loss = m(ids, labels=ids).mean()
        loss.backward(); opt.step(); opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]
