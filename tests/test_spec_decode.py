"""Speculative decoding for the serving engine (ISSUE-7).

Covers the tentpole contracts: greedy speculative streams bit-identical
to solo `generation.generate` regardless of draft quality, heterogeneous
spec on/off + sampling-param traffic sharing the single verify trace
(compile bound unchanged at len(prefill_buckets) + 1), spec-off slots
reproducing the plain engine token-for-token, distribution preservation
of the rejection-sampling commit (the Leviathan/Chen theorem, checked
empirically), the PR-6 deadline rule across multi-token ticks, and the
PDTPU_FAULT_DRAFT_DIVERGE degradation path."""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import models
from paddle_tpu.core.errors import InvalidArgumentError
from paddle_tpu.nn.layer_base import Layer
from paddle_tpu.serving import ServingEngine, DeadlineExceededError
from paddle_tpu.utils import faults
from paddle_tpu.utils.monitor import stat_get

pytestmark = [pytest.mark.spec, pytest.mark.serving]


def tiny_gpt(layers=2, seed=7):
    cfg = models.GPTConfig(vocab_size=13, hidden_size=16,
                           num_hidden_layers=layers, num_attention_heads=2,
                           hidden_dropout_prob=0.0,
                           attention_probs_dropout_prob=0.0,
                           max_position_embeddings=64)
    paddle.seed(seed)
    m = models.GPTForPretraining(cfg)
    m.eval()
    return m


def solo(model, prompt, max_new, **kw):
    out, _ = model.generate(paddle.to_tensor(
        np.asarray(prompt, np.int32)[None]), max_new_tokens=max_new, **kw)
    return np.asarray(out.numpy())[0].tolist()


@pytest.fixture(scope="module")
def spec_engine():
    """Target GPT + an unrelated (random-weight) 1-layer draft: the
    worst-case draft — parity must hold no matter how bad the proposals
    are."""
    target = tiny_gpt(layers=2, seed=7)
    draft = tiny_gpt(layers=1, seed=11)
    eng = ServingEngine(target, max_slots=3, max_len=48,
                        prefill_buckets=(8, 16), draft_model=draft,
                        spec_tokens=3, max_queue_depth=64)
    eng.warmup()
    return target, eng


# ---------------------------------------------------------------------------
# greedy parity: bit-identical to solo generate, any draft
# ---------------------------------------------------------------------------

def test_spec_greedy_parity_random_draft(spec_engine):
    target, eng = spec_engine
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, 13, (n,)) for n in (4, 7, 11)]
    resps = [eng.submit(p, max_new_tokens=6) for p in prompts]
    eng.run_until_drained(timeout=120)
    for p, r in zip(prompts, resps):
        assert r.tokens(timeout=5) == solo(target, p, 6)
        assert r.finish_reason == "length"


def test_spec_identical_draft_accepts_everything():
    """Draft == target (weight-identical clone): every proposal matches
    the target argmax, so accept rate is exactly 1.0 — this also proves
    the K+1-token verify forward is row-for-row bit-identical to the
    draft's sequential single-token forwards."""
    target = tiny_gpt(layers=2, seed=7)
    clone = tiny_gpt(layers=2, seed=7)
    eng = ServingEngine(target, max_slots=2, max_len=48,
                        prefill_buckets=(8,), draft_model=clone,
                        spec_tokens=3)
    r = eng.submit([1, 2, 3, 4], max_new_tokens=9)
    eng.run_until_drained(timeout=120)
    assert r.tokens() == solo(target, [1, 2, 3, 4], 9)
    assert eng.metrics()["spec"]["accept_rate"] == 1.0


def test_spec_eos_stops_stream_and_frees_slot(spec_engine):
    target, eng = spec_engine
    prompt = [1, 2, 3]
    toks = solo(target, prompt, 6)
    eos = toks[2]  # lands mid-tick (spec_tokens=3 commits up to 4)
    r = eng.submit(prompt, max_new_tokens=6, eos_token_id=eos)
    eng.run_until_drained(timeout=120)
    assert r.tokens() == toks[:toks.index(eos) + 1]
    assert r.finish_reason == "eos"
    assert eng.scheduler.free_slot_count() == eng.max_slots


# ---------------------------------------------------------------------------
# one verify trace for every traffic mix
# ---------------------------------------------------------------------------

def test_spec_compile_bound_over_heterogeneous_traffic(spec_engine):
    """spec on/off × greedy/sampling × distinct sampling params share the
    verify trace: zero retraces across 16 mixed requests."""
    from paddle_tpu.core import op as core_op
    _, eng = spec_engine
    combos = [
        dict(max_new_tokens=3),
        dict(max_new_tokens=4, spec=False),
        dict(max_new_tokens=5, decode_strategy="sampling",
             temperature=0.7, seed=1),
        dict(max_new_tokens=4, decode_strategy="sampling", top_k=3,
             seed=2, spec=False),
        dict(max_new_tokens=6, decode_strategy="sampling", top_p=0.8,
             temperature=1.3, seed=3),
    ]
    rng = np.random.RandomState(0)
    before = eng.compile_counts()
    disp_before = core_op.dispatch_cache_stats()["misses"]
    resps = []
    for i in range(16):
        plen = int(rng.randint(2, 8))
        resps.append(eng.submit(rng.randint(0, 13, (plen,)),
                                **combos[i % len(combos)]))
        eng.step()
    eng.run_until_drained(timeout=120)
    for r in resps:
        assert r.done() and r.error is None
    after = eng.compile_counts()
    assert after == before, "mixed spec/sampling traffic must not retrace"
    assert after["total"] <= after["bound"] == len(eng.buckets) + 1
    assert core_op.dispatch_cache_stats()["misses"] == disp_before


def test_spec_off_matches_plain_engine_bit_exact(spec_engine):
    """A sampling request with spec=False inside a speculative engine
    must stream token-for-token what the plain continuous-batching
    engine produces for the same seed (same key folds, same
    distributions)."""
    target, eng = spec_engine
    kw = dict(max_new_tokens=8, decode_strategy="sampling", top_k=4,
              temperature=0.9, seed=9)
    off = eng.submit([1, 2, 3], spec=False, **kw)
    eng.run_until_drained(timeout=60)
    plain = ServingEngine(target, max_slots=2, max_len=48,
                          prefill_buckets=(8,))
    p = plain.submit([1, 2, 3], **kw)
    plain.run_until_drained(timeout=60)
    assert off.tokens() == p.tokens()


def test_spec_sampling_deterministic_per_seed(spec_engine):
    _, eng = spec_engine
    kw = dict(max_new_tokens=5, decode_strategy="sampling", top_k=4,
              seed=17)
    a = eng.submit([2, 4, 6], **kw)
    eng.run_until_drained(timeout=60)
    b = eng.submit([2, 4, 6], **kw)
    eng.run_until_drained(timeout=60)
    assert a.tokens() == b.tokens()


class _MarkerModel(Layer):
    """Clamp-detector protocol model: KV rows hold (position + token)
    markers and the greedy token is the masked prefix-sum mod vocab — a
    single misplaced/clamped KV write changes the stream immediately
    (real transformer logits can shrug off one corrupted row; this
    cannot)."""

    VOCAB = 97

    def gen_fixed_cache(self, batch_size, max_length, dtype=None):
        import jax.numpy as jnp
        dt = dtype or jnp.float32
        return [(jnp.zeros((batch_size, max_length, 1, 1), dt),
                 jnp.zeros((batch_size, max_length, 1, 1), dt))]

    def forward_fixed(self, input_ids, caches, pos):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.core.tensor import unwrap
        ids = unwrap(input_ids)
        p = unwrap(pos)
        s = ids.shape[1]
        k, v = caches[0]
        marker = (p + jnp.arange(s)[None, :] + 1 + ids).astype(k.dtype)
        k = jax.lax.dynamic_update_slice(k, marker[:, :, None, None],
                                         (0, p, 0, 0))
        t = k.shape[1]
        key_idx = jnp.arange(t)[None, None, :]
        q_idx = (p + jnp.arange(s))[None, :, None]
        mask = (key_idx <= q_idx).astype(k.dtype)
        sums = jnp.sum(k[:, :, 0, 0][:, None, :] * mask, axis=-1)
        tok = jnp.mod(sums, self.VOCAB).astype(jnp.int32)
        return jax.nn.one_hot(tok, self.VOCAB, dtype=jnp.float32), [(k, v)]


def test_spec_full_budget_request_keeps_parity_at_pool_end():
    """A request using the ENTIRE plen+max_new == max_len budget: the
    final verify ticks write K+1 rows near the end of the pool, which
    must land in the engine's spec headroom — without it
    dynamic_update_slice would CLAMP the write start and silently
    overwrite committed KV, corrupting the tail of the stream.  The
    spec=False request advances one position per tick, so its last
    ticks provably write past max_len (the clamp trigger); the marker
    model makes any clamp visible in the stream (regression for the
    pool-length bound)."""
    from paddle_tpu.generation import generate
    m = _MarkerModel()
    eng = ServingEngine(m, max_slots=2, max_len=16, prefill_buckets=(8,),
                        draft_model=_MarkerModel(), spec_tokens=3)
    r_off = eng.submit([1, 2, 3, 4], max_new_tokens=12, spec=False)
    r_on = eng.submit([5, 6, 7, 8], max_new_tokens=12)
    eng.run_until_drained(timeout=120)

    def oracle(prompt):
        out, _ = generate(m, paddle.to_tensor(
            np.asarray(prompt, np.int32)[None]), max_new_tokens=12)
        return np.asarray(out.numpy())[0].tolist()

    assert r_off.tokens() == oracle([1, 2, 3, 4])
    assert r_on.tokens() == oracle([5, 6, 7, 8])
    # and the same full-budget shape on a real model
    target = tiny_gpt(layers=2, seed=7)
    geng = ServingEngine(target, max_slots=1, max_len=16,
                         prefill_buckets=(8,),
                         draft_model=tiny_gpt(layers=1, seed=11),
                         spec_tokens=3)
    g = geng.submit([1, 2, 3, 4], max_new_tokens=12)
    geng.run_until_drained(timeout=120)
    assert g.tokens() == solo(target, [1, 2, 3, 4], 12)


def test_spec_requires_draft_and_valid_k():
    target = tiny_gpt()
    plain = ServingEngine(target, max_slots=2, max_len=48,
                          prefill_buckets=(8,))
    with pytest.raises(InvalidArgumentError, match="draft_model"):
        plain.submit([1, 2], max_new_tokens=2, spec=True)
    with pytest.raises(InvalidArgumentError, match="spec_tokens"):
        ServingEngine(target, max_slots=2, max_len=48,
                      prefill_buckets=(8,), draft_model=tiny_gpt(1, 3),
                      spec_tokens=0)


# ---------------------------------------------------------------------------
# distribution preservation (the rejection-sampling theorem, empirically)
# ---------------------------------------------------------------------------

def test_spec_sampled_commit_preserves_target_distribution():
    """The first committed token of a speculative tick must follow the
    PROCESSED TARGET distribution exactly, however bad the draft is:
    empirical TV distance over 4000 independent keys < 0.05."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.generation.speculative import (
        commit_speculative_sampled, draft_proposal_key)
    n, v, k = 4000, 5, 2
    rng = np.random.RandomState(0)
    p_logits = jnp.asarray(rng.randn(v).astype(np.float32)) * 1.5
    q_logits = jnp.asarray(rng.randn(v).astype(np.float32)) * 1.5  # != p
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(n))
    pos = jnp.zeros((n,), jnp.int32)
    # draft proposals drawn from q with the engine's key derivation
    qs = jnp.broadcast_to(jax.nn.softmax(q_logits), (n, k, v))
    props = jnp.stack([
        jax.vmap(lambda kk, i=i: jax.random.categorical(
            draft_proposal_key(kk, 0, i), q_logits))(keys)
        for i in range(k)], axis=1).astype(jnp.int32)
    plog = jnp.broadcast_to(p_logits, (n, k + 1, v))
    out, count, accepted, last, lp = commit_speculative_sampled(
        props, qs, plog, keys, pos, jnp.zeros((n,), bool),
        jnp.ones((n,), bool), 0)
    first = np.asarray(out[:, 0])
    emp = np.bincount(first, minlength=v) / n
    want = np.asarray(jax.nn.softmax(p_logits))
    tv = 0.5 * np.abs(emp - want).sum()
    assert tv < 0.05, (tv, emp, want)
    # sanity: the draft disagrees enough that rejections actually happen
    assert float(jnp.mean(accepted)) < k


# ---------------------------------------------------------------------------
# PR-6 deadline rule across multi-token ticks (satellite regression)
# ---------------------------------------------------------------------------

@pytest.mark.faults
def test_spec_deadline_mid_tick_delivers_no_post_expiry_tokens():
    """A verify tick can commit up to K+1 tokens; a deadline that expires
    while the tick is computing must deliver NONE of them (deadline
    shorter than one speculative tick — the tick is slowed with the
    slow_decode fault)."""
    target = tiny_gpt(layers=1, seed=3)
    draft = tiny_gpt(layers=1, seed=4)
    eng = ServingEngine(target, max_slots=2, max_len=48,
                        prefill_buckets=(8,), draft_model=draft,
                        spec_tokens=4)
    eng.warmup()
    faults.enable("slow_decode", "120")  # every tick sleeps 120 ms
    try:
        r = eng.submit([1, 2, 3], max_new_tokens=20, deadline=0.06)
        eng.step()   # prefill (1 token) + one slowed tick
        eng.step()
    finally:
        faults.reset()
    with pytest.raises(DeadlineExceededError):
        r.tokens(timeout=5)
    # only the prefill token (emitted before expiry) may have streamed:
    # the expired tick's K+1 ready commits were all withheld
    assert len(r.tokens_so_far()) <= 1
    assert eng.scheduler.free_slot_count() == eng.max_slots


# ---------------------------------------------------------------------------
# PDTPU_FAULT_DRAFT_DIVERGE (satellite)
# ---------------------------------------------------------------------------

@pytest.mark.faults
def test_draft_diverge_degrades_to_target_only_without_corruption():
    """Draft poisoned EVERY tick (diverge stride 1): the accept/reject
    path must reject essentially everything — throughput falls to
    target-only — while every stream stays bit-identical to solo
    generate."""
    target = tiny_gpt(layers=2, seed=7)
    clone = tiny_gpt(layers=2, seed=7)  # accept rate would be 1.0 clean
    faults.enable("draft_diverge", "1")
    try:
        eng = ServingEngine(target, max_slots=2, max_len=48,
                            prefill_buckets=(8,), draft_model=clone,
                            spec_tokens=3)
        assert eng._diverge_every == 1
        acc0 = stat_get("STAT_spec_accepted")
        r0 = eng.submit([1, 2, 3, 4], max_new_tokens=9)
        r1 = eng.submit([5, 6, 7], max_new_tokens=9,
                        decode_strategy="sampling", top_k=5, seed=5)
        eng.run_until_drained(timeout=120)
    finally:
        faults.reset()
    assert r0.tokens() == solo(target, [1, 2, 3, 4], 9)
    assert r1.error is None and len(r1.tokens()) == 9
    met = eng.metrics()["spec"]
    assert met["accept_rate"] < 0.2, met
    assert stat_get("STAT_spec_accepted") - acc0 <= met["proposed"] * 0.2


def test_clean_engine_has_no_diverge_branch(spec_engine):
    _, eng = spec_engine
    assert eng._diverge_every is None


# ---------------------------------------------------------------------------
# observability: accept histogram, verify program tracking, STAT counters
# ---------------------------------------------------------------------------

def test_spec_metrics_and_program_tracking(spec_engine):
    from paddle_tpu import observability as obs
    _, eng = spec_engine
    ticks0 = stat_get("STAT_spec_ticks")
    r = eng.submit([3, 1, 4], max_new_tokens=5)
    eng.run_until_drained(timeout=60)
    assert r.done()
    met = eng.metrics()["spec"]
    assert met["enabled"] and met["spec_tokens"] == 3
    assert met["proposed"] > 0 and met["accept_rate"] is not None
    assert stat_get("STAT_spec_ticks") > ticks0
    assert stat_get("STAT_spec_proposed") >= met["proposed"]
    # the verify + spec-prefill programs are first-class registry entries
    names = list(obs.get_program_registry().names())
    assert "serving_verify" in names
    assert any(n.startswith("serving_prefill_spec_b") for n in names)
    # the accept-rate histogram is registered and populated
    reg = obs.get_registry()
    h = reg.snapshot().get("serving_spec_accept_rate")
    assert h is not None


def test_plain_engine_metrics_say_spec_disabled():
    target = tiny_gpt()
    eng = ServingEngine(target, max_slots=2, max_len=48,
                        prefill_buckets=(8,))
    assert eng.metrics()["spec"] == {"enabled": False}


# ---------------------------------------------------------------------------
# probe smoke (fresh interpreter: slow tier)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_spec_decode_probe_smoke():
    import json
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable,
         os.path.join(repo, "probes", "spec_decode_probe.py"),
         "--steps", "3"],
        capture_output=True, text=True, timeout=600, env=env, cwd=repo)
    assert proc.returncode == 0, (proc.stderr or proc.stdout)[-800:]
    lines = [l for l in proc.stdout.splitlines() if l.startswith("SPEC")]
    assert lines, proc.stdout[-400:]
    out = json.loads(lines[-1][len("SPEC"):])
    assert out["smoke"] is True
    assert "failures" not in out, out.get("failures")
    for leg in ("spec_decode", "quant"):
        cc = out[leg]["compile_counts"]
        assert cc["total"] <= cc["bound"]
    assert out["quant"]["max_logit_err"] >= 0
