"""One end-to-end user journey across the framework surface: build a model,
train it (fused TrainStep + AMP), checkpoint, restore into a fresh process
state, generate text, export, and serve — the workflow a reference user
migrates wholesale (reference: the book tests + save_inference_model +
AnalysisPredictor chain)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import models

pytestmark = pytest.mark.slow


def _cfg():
    return models.GPTConfig(vocab_size=32, hidden_size=32,
                            num_hidden_layers=2, num_attention_heads=2,
                            hidden_dropout_prob=0.0,
                            attention_probs_dropout_prob=0.0,
                            max_position_embeddings=64)


def test_full_user_journey(tmp_path):
    from paddle_tpu.jit import TrainStep
    paddle.seed(0)
    rng = np.random.RandomState(0)
    model = models.GPTForPretraining(_cfg())
    crit = models.GPTPretrainingCriterion()
    opt = paddle.optimizer.AdamW(learning_rate=5e-3,
                                 parameters=model.parameters())
    step = TrainStep(model, crit, opt, amp_level="O1")

    # 1. train on a repeating pattern until loss drops
    pattern = np.tile(np.arange(8), 4).astype("int32")
    ids = paddle.to_tensor(np.tile(pattern, (4, 1)))
    first = last = None
    for i in range(40):
        loss = float(step(ids, ids))
        first = loss if first is None else first
        last = loss
    assert last < first * 0.7, (first, last)

    # 2. checkpoint + restore into a FRESH model: trajectory continues
    ckdir = str(tmp_path / "ck")
    step.save_checkpoint(ckdir)
    paddle.seed(123)  # different init to prove restore overwrites it
    model2 = models.GPTForPretraining(_cfg())
    opt2 = paddle.optimizer.AdamW(learning_rate=5e-3,
                                  parameters=model2.parameters())
    step2 = TrainStep(model2, crit, opt2, amp_level="O1")
    assert step2.restore_checkpoint(ckdir) is not None
    resumed = float(step2(ids, ids))
    assert abs(resumed - last) < 0.5, (resumed, last)

    # 3. generate a continuation: the jitted decode loop agrees with a
    # step-by-step eager argmax rollout of the restored model
    model2.eval()
    prompt = paddle.to_tensor(pattern[None, :6].astype("int32"))
    out, _ = model2.generate(prompt, max_new_tokens=4)
    seq = pattern[None, :6].astype("int32").copy()
    for _ in range(4):
        nxt = model2(paddle.to_tensor(seq)).numpy()[:, -1].argmax(-1)
        seq = np.concatenate([seq, nxt[:, None].astype("int32")], axis=1)
    np.testing.assert_array_equal(out.numpy()[0], seq[0, 6:])

    # 4. export + serve: jit.load and the inference Predictor agree with
    # the live model on the same input
    prefix = str(tmp_path / "served")
    paddle.jit.save(model2, prefix,
                    input_spec=[paddle.static.InputSpec([1, 6], "int32")])
    served = paddle.jit.load(prefix)
    live = model2(prompt).numpy()
    np.testing.assert_allclose(served(prompt).numpy(), live, rtol=1e-4,
                               atol=1e-4)

    from paddle_tpu import inference
    pred = inference.create_predictor(inference.Config(prefix))
    h = pred.get_input_handle(pred.get_input_names()[0])
    h.copy_from_cpu(prompt.numpy())
    pred.run()
    out_h = pred.get_output_handle(pred.get_output_names()[0])
    np.testing.assert_allclose(out_h.copy_to_cpu(), live, rtol=1e-4,
                               atol=1e-4)
