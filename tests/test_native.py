"""Native C++ datafeed tests (reference analogue: data_feed unit tests +
buffered_reader tests)."""
import os

import numpy as np
import pytest

from paddle_tpu import native


pytestmark = pytest.mark.skipif(not native.available(),
                                reason="g++ build unavailable")


def test_text_feed_roundtrip(tmp_path):
    rng = np.random.RandomState(0)
    dim = 8
    rows = []
    for fi in range(3):
        lines = []
        for _ in range(25):
            label = rng.randint(0, 10)
            feats = rng.randn(dim).astype(np.float32)
            rows.append((label, feats))
            lines.append(f"{label}\t" + ",".join(f"{v:.6f}" for v in feats))
        (tmp_path / f"part-{fi}.txt").write_text("\n".join(lines) + "\n")

    feed = native.TextSlotDataFeed(
        [str(tmp_path / f"part-{i}.txt") for i in range(3)],
        batch_size=16, dim=dim, n_threads=2)
    got = []
    for feats, labels in feed:
        assert feats.shape[1] == dim
        for f, l in zip(feats, labels):
            got.append((int(l), f))
    assert len(got) == 75
    # content matches irrespective of thread interleaving: compare sorted sums
    want_sum = sorted(float(f.sum()) + l for l, f in rows)
    got_sum = sorted(float(f.sum()) + l for l, f in got)
    np.testing.assert_allclose(got_sum, want_sum, rtol=1e-4, atol=1e-4)


def test_binary_feed_roundtrip(tmp_path):
    rng = np.random.RandomState(1)
    feats = rng.randn(40, 16).astype(np.float32)
    labels = rng.randint(0, 5, (40,)).astype(np.int64)
    path = str(tmp_path / "data.bin")
    native.write_binary_slot_file(path, feats, labels)

    feed = native.TextSlotDataFeed([path], batch_size=8, dim=16,
                                   n_threads=1, binary=True)
    got_f, got_l = [], []
    for f, l in feed:
        got_f.append(f)
        got_l.append(l)
    got_f = np.concatenate(got_f)
    got_l = np.concatenate(got_l)
    np.testing.assert_allclose(got_f, feats, rtol=1e-6)
    np.testing.assert_array_equal(got_l, labels)


def test_malformed_lines_skipped(tmp_path):
    p = tmp_path / "bad.txt"
    p.write_text("1\t1.0,2.0\nnot_a_label\t3.0,4.0\n2\t5.0\n3\t7.0,8.0\n")
    feed = native.TextSlotDataFeed([str(p)], batch_size=4, dim=2)
    batches = list(feed)
    total = sum(len(l) for _, l in batches)
    assert total == 2  # only the two well-formed rows survive


def test_drop_last(tmp_path):
    p = tmp_path / "d.txt"
    p.write_text("\n".join(f"{i}\t1.0,2.0" for i in range(10)))
    feed = native.TextSlotDataFeed([str(p)], batch_size=4, dim=2,
                                   n_threads=1, drop_last=True)
    sizes = [len(l) for _, l in feed]
    assert all(s == 4 for s in sizes) and sum(sizes) == 8
