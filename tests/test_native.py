"""Native C++ datafeed tests (reference analogue: data_feed unit tests +
buffered_reader tests)."""
import os

import numpy as np
import pytest

from paddle_tpu import native


pytestmark = pytest.mark.skipif(not native.available(),
                                reason="g++ build unavailable")


def test_text_feed_roundtrip(tmp_path):
    rng = np.random.RandomState(0)
    dim = 8
    rows = []
    for fi in range(3):
        lines = []
        for _ in range(25):
            label = rng.randint(0, 10)
            feats = rng.randn(dim).astype(np.float32)
            rows.append((label, feats))
            lines.append(f"{label}\t" + ",".join(f"{v:.6f}" for v in feats))
        (tmp_path / f"part-{fi}.txt").write_text("\n".join(lines) + "\n")

    feed = native.TextSlotDataFeed(
        [str(tmp_path / f"part-{i}.txt") for i in range(3)],
        batch_size=16, dim=dim, n_threads=2)
    got = []
    for feats, labels in feed:
        assert feats.shape[1] == dim
        for f, l in zip(feats, labels):
            got.append((int(l), f))
    assert len(got) == 75
    # content matches irrespective of thread interleaving: compare sorted sums
    want_sum = sorted(float(f.sum()) + l for l, f in rows)
    got_sum = sorted(float(f.sum()) + l for l, f in got)
    np.testing.assert_allclose(got_sum, want_sum, rtol=1e-4, atol=1e-4)


def test_binary_feed_roundtrip(tmp_path):
    rng = np.random.RandomState(1)
    feats = rng.randn(40, 16).astype(np.float32)
    labels = rng.randint(0, 5, (40,)).astype(np.int64)
    path = str(tmp_path / "data.bin")
    native.write_binary_slot_file(path, feats, labels)

    feed = native.TextSlotDataFeed([path], batch_size=8, dim=16,
                                   n_threads=1, binary=True)
    got_f, got_l = [], []
    for f, l in feed:
        got_f.append(f)
        got_l.append(l)
    got_f = np.concatenate(got_f)
    got_l = np.concatenate(got_l)
    np.testing.assert_allclose(got_f, feats, rtol=1e-6)
    np.testing.assert_array_equal(got_l, labels)


def test_malformed_lines_skipped(tmp_path):
    p = tmp_path / "bad.txt"
    p.write_text("1\t1.0,2.0\nnot_a_label\t3.0,4.0\n2\t5.0\n3\t7.0,8.0\n")
    feed = native.TextSlotDataFeed([str(p)], batch_size=4, dim=2)
    batches = list(feed)
    total = sum(len(l) for _, l in batches)
    assert total == 2  # only the two well-formed rows survive


def test_drop_last(tmp_path):
    p = tmp_path / "d.txt"
    p.write_text("\n".join(f"{i}\t1.0,2.0" for i in range(10)))
    feed = native.TextSlotDataFeed([str(p)], batch_size=4, dim=2,
                                   n_threads=1, drop_last=True)
    sizes = [len(l) for _, l in feed]
    assert all(s == 4 for s in sizes) and sum(sizes) == 8


# -- multiprocess DataLoader (VERDICT r1 missing #6) --------------------------

class _SlowDataset:
    """Map-style dataset with per-item cost, to expose worker parallelism."""

    def __init__(self, n=48, delay=0.01):
        import numpy as _np
        self.n = n
        self.delay = delay
        self.rng = _np.random.RandomState(0)

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        import time as _t
        import numpy as _np
        _t.sleep(self.delay)
        x = _np.full((64, 64), float(i), "float32")
        return x, _np.asarray([i], "int64")


def test_mp_dataloader_correct_and_ordered():
    from paddle_tpu.io import DataLoader
    ds = _SlowDataset(n=24, delay=0.0)
    dl = DataLoader(ds, batch_size=4, num_workers=3, shuffle=False)
    seen = []
    for xb, yb in dl:
        assert tuple(xb.shape) == (4, 64, 64)
        seen.extend(np.asarray(yb.numpy()).reshape(-1).tolist())
    assert seen == list(range(24))  # ordered despite parallel workers


def test_mp_dataloader_parallel_speedup():
    import time
    from paddle_tpu.io import DataLoader
    ds = _SlowDataset(n=32, delay=0.02)

    def epoch(workers):
        # persistent workers + warmup epoch: time steady-state throughput,
        # not process-fork startup (which dominates under a loaded runner)
        dl = DataLoader(ds, batch_size=4, num_workers=workers,
                        persistent_workers=True)
        for _ in dl:
            pass
        t0 = time.perf_counter()
        for _ in dl:
            pass
        dt = time.perf_counter() - t0
        if dl._pool is not None:
            dl._pool.shutdown()
        return dt

    serial = epoch(0)
    # one retry: absorbs scheduler noise on a loaded runner
    for attempt in range(2):
        parallel = epoch(4)
        if parallel < serial * 0.6:
            break
    # 32 items x 20ms = 640ms serial floor; 4 workers should beat 60% of it
    assert parallel < serial * 0.6, (serial, parallel)


def test_mp_dataloader_worker_init_and_persistent():
    import os
    from paddle_tpu.io import DataLoader
    marker = []

    def init_fn(wid):
        # runs in the worker process: write a marker file
        open(f"/tmp/pt_worker_{os.getpid()}_{wid}", "w").close()
        marker.append(wid)  # only visible in the worker, not the parent

    ds = _SlowDataset(n=8, delay=0.0)
    dl = DataLoader(ds, batch_size=2, num_workers=2, worker_init_fn=init_fn,
                    persistent_workers=True)
    for _ in dl:
        pass
    pool1 = dl._pool
    assert pool1 is not None and pool1.alive()  # persistent: still up
    for _ in dl:
        pass
    assert dl._pool is pool1  # same workers across epochs
    pool1.shutdown()
    assert marker == []  # init ran in workers, not the parent


def test_mp_dataloader_worker_error_propagates():
    from paddle_tpu.io import DataLoader

    class Bad(_SlowDataset):
        def __getitem__(self, i):
            if i == 5:
                raise ValueError("boom")
            return super().__getitem__(i)

    dl = DataLoader(Bad(n=8, delay=0.0), batch_size=2, num_workers=2)
    with pytest.raises(RuntimeError, match="boom"):
        for _ in dl:
            pass


def test_mp_dataloader_early_break_then_new_epoch_no_stale_batches():
    """Breaking out of iteration mid-epoch (persistent workers) must not
    leak the in-flight batches into the next epoch."""
    from paddle_tpu.io import DataLoader
    ds = _SlowDataset(n=32, delay=0.0)
    dl = DataLoader(ds, batch_size=2, num_workers=3,
                    persistent_workers=True)
    it = iter(dl)
    first = next(it)
    assert np.asarray(first[1].numpy()).reshape(-1).tolist() == [0, 1]
    del it  # abandon mid-epoch with many batches in flight
    import gc
    gc.collect()
    seen = []
    for xb, yb in dl:  # fresh epoch must start at 0 and stay ordered
        seen.extend(np.asarray(yb.numpy()).reshape(-1).tolist())
    assert seen == list(range(32))
    dl._pool.shutdown()


def test_mp_dataloader_concurrent_iterators():
    """Two simultaneous iterators over one loader must both see a complete,
    ordered epoch (the second gets a private temporary pool)."""
    from paddle_tpu.io import DataLoader
    ds = _SlowDataset(n=12, delay=0.0)
    dl = DataLoader(ds, batch_size=2, num_workers=2,
                    persistent_workers=True)
    it1, it2 = iter(dl), iter(dl)
    got1, got2 = [], []
    for _ in range(6):
        got1.extend(np.asarray(next(it1)[1].numpy()).reshape(-1).tolist())
        got2.extend(np.asarray(next(it2)[1].numpy()).reshape(-1).tolist())
    assert got1 == list(range(12)) and got2 == list(range(12))
    if dl._pool is not None:
        dl._pool.shutdown()


def test_mp_dataloader_no_shm_leak_on_early_break():
    """Shared-memory blocks from abandoned in-flight batches must be freed."""
    import glob
    from paddle_tpu.io import DataLoader
    before = len(glob.glob("/dev/shm/psm_*")) + len(glob.glob("/dev/shm/mp-*"))
    ds = _SlowDataset(n=64, delay=0.0)  # 64x64 f32 = 16KB >= shm threshold
    for _ in range(3):
        dl = DataLoader(ds, batch_size=4, num_workers=3)
        it = iter(dl)
        next(it)
        del it  # abandon with in-flight shm batches
        import gc
        gc.collect()
        del dl
        gc.collect()
    import time
    time.sleep(0.5)
    after = len(glob.glob("/dev/shm/psm_*")) + len(glob.glob("/dev/shm/mp-*"))
    assert after <= before + 1, (before, after)  # no unbounded growth


def test_grad_scaler_multi_optimizer_interleave():
    """scale() for a second loss must not reset another optimizer's unscale
    guard (GAN-style interleave would silently double-divide grads)."""
    import paddle_tpu as paddle
    la = paddle.nn.Linear(4, 4)
    lb = paddle.nn.Linear(4, 4)
    opt_a = paddle.optimizer.SGD(learning_rate=0.0, parameters=la.parameters())
    opt_b = paddle.optimizer.SGD(learning_rate=0.0, parameters=lb.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=128.0)
    x = paddle.ones([2, 4])
    loss_a = la(x).sum()
    scaler.scale(loss_a).backward()
    scaler.unscale_(opt_a)
    g_after_unscale = np.asarray(la.weight.grad.numpy()).copy()
    # interleaved second loss: must NOT clear opt_a's guard
    loss_b = lb(x).sum()
    scaler.scale(loss_b).backward()
    scaler.step(opt_a)   # internal unscale_ must be a no-op for opt_a
    scaler.step(opt_b)
    np.testing.assert_allclose(np.asarray(la.weight.grad.numpy()),
                               g_after_unscale, rtol=1e-6)
