"""Batched multi-tenant LoRA (ISSUE 19): train-side rank-r wrappers,
export -> registry round-trip over the sha256-verified artifact format,
and serve-side batched adapters where the per-slot adapter id is a
DYNAMIC input to the same compiled program family — heterogeneous
adapters batch in one tick at the unchanged compile bound, adapter id 0
is bit-identical to a no-LoRA engine, and hot-load reaches subprocess
workers over the chunked verified channel.

Tier-1 keeps every engine test on the tiny GPT with one prefill bucket
and <= 8-token decodes; the fleet hot-load smoke uses one REMOTE
--listen worker under a hard SIGALRM timeout (the subprocess-worker
variant rides `slow`).  The throughput/ship-latency bars live in
probes/lora_probe.py (bench `detail.lora`), smoked under `slow`.
"""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import lora, models, nn
from paddle_tpu import optimizer as popt
from paddle_tpu.core.errors import InvalidArgumentError
from paddle_tpu.lora import (AdapterExhaustedError, AdapterIntegrityError,
                             AdapterNotFoundError, AdapterRegistry,
                             LoRAConfig, base_weights_hash)
from paddle_tpu.serving import (FleetRouter, ServingEngine, ServingGateway,
                                TenantConfig)
from paddle_tpu.utils import faults

pytestmark = pytest.mark.lora

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

GPT_KW = dict(vocab_size=64, hidden_size=32, num_hidden_layers=2,
              num_attention_heads=2, hidden_dropout_prob=0.0,
              attention_probs_dropout_prob=0.0,
              max_position_embeddings=128)
ENGINE_KW = dict(max_slots=4, max_len=64, prefill_buckets=(8,),
                 decode_chunk=2)
LORA_CFG = dict(rank=4, max_adapters=3, targets=("qkv",))


def tiny_model(seed=11):
    paddle.seed(seed)
    m = models.GPTForPretraining(models.GPTConfig(**GPT_KW))
    m.eval()
    return m


def lora_wrapped(factor_seed, base_seed=11, rank=4, targets=("qkv",)):
    """A LoRA-wrapped tiny GPT with deterministic NONZERO factors (a
    fresh wrap has B=0 and would be the base model verbatim)."""
    m = tiny_model(base_seed)
    lora.apply_lora(m, rank=rank, targets=targets)
    rng = np.random.default_rng(factor_seed)
    for lyr in m.sublayers(include_self=True):
        if isinstance(lyr, lora.LoRALinear):
            lyr.lora_A._data = paddle.to_tensor(
                rng.normal(0, 0.2, lyr.lora_A.shape).astype("float32"))._data
            lyr.lora_B._data = paddle.to_tensor(
                rng.normal(0, 0.2, lyr.lora_B.shape).astype("float32"))._data
    return m


@pytest.fixture(scope="module")
def adapters(tmp_path_factory):
    """Three exported adapter artifacts against the seed-11 base
    (module-scoped: exports are deterministic and no test mutates
    them)."""
    tmp = tmp_path_factory.mktemp("lora_adapters")
    out = {}
    for name, seed in (("a1", 101), ("a2", 202), ("a3", 303)):
        path = str(tmp / f"{name}.npz")
        sha = lora.export_adapter(lora_wrapped(seed), path)
        out[name] = (path, sha)
    return out


def drain(eng, timeout=120):
    t0 = time.monotonic()
    while eng.has_work():
        eng.step()
        if time.monotonic() - t0 > timeout:
            raise AssertionError("engine drain timeout")


def stream(eng, prompt, max_new, adapter=None):
    resp = eng.submit(prompt, max_new, adapter=adapter)
    drain(eng)
    return resp.tokens(timeout=5)


def serving_compiles():
    from paddle_tpu import observability
    reg = observability.get_program_registry()
    return {k: v["compiles"] for k, v in reg.snapshot().items()
            if k.startswith("serving_")}


# ---------------------------------------------------------------------------
# train side: eager parity, frozen base
# ---------------------------------------------------------------------------

def test_lora_linear_matches_dense_merged_oracle():
    """y = base(x) + scaling*(x@A)@B must equal the dense layer built
    from merged_weight() — the offline-merge contract; and a fresh wrap
    (B=0) is the base layer bit-for-bit."""
    paddle.seed(3)
    base = nn.Linear(16, 24)
    x = paddle.to_tensor(np.random.default_rng(0).normal(
        0, 1, (5, 16)).astype("float32"))
    before = base(x).numpy()
    wrapped = lora.LoRALinear(base, rank=4)
    np.testing.assert_array_equal(wrapped(x).numpy(), before)
    rng = np.random.default_rng(1)
    wrapped.lora_A._data = paddle.to_tensor(
        rng.normal(0, 0.3, (16, 4)).astype("float32"))._data
    wrapped.lora_B._data = paddle.to_tensor(
        rng.normal(0, 0.3, (4, 24)).astype("float32"))._data
    want = x.numpy() @ np.asarray(wrapped.merged_weight())
    want = want + base.bias.numpy()
    np.testing.assert_allclose(wrapped(x).numpy(), want, atol=1e-5)


def test_apply_lora_freezes_base_and_trains_only_factors():
    """apply_lora leaves ONLY the rank-r factors trainable; optimizer
    steps move them while every base parameter (and the recorded base
    hash) stays bit-identical — the frozen-base proof."""
    m = tiny_model()
    base_hash = base_weights_hash(m)
    wrapped = lora.apply_lora(m, rank=4, targets=("qkv",))
    assert len(wrapped) == GPT_KW["num_hidden_layers"]
    trainable = [p for p in m.parameters() if p.trainable]
    assert trainable and all(
        any(s in n for s in ("lora_A", "lora_B"))
        for n, _ in m.named_parameters() if _.trainable)
    base_snap = {n: p.numpy().copy() for n, p in m.named_parameters()
                 if not p.trainable}
    o = popt.Adam(0.05, parameters=trainable)
    ids = paddle.to_tensor(np.arange(1, 9, dtype=np.int64)[None])
    labels = paddle.to_tensor(np.arange(2, 10, dtype=np.int64)[None])
    losses = m(ids, labels=labels)
    losses.sum().backward()
    o.step()
    o.clear_grad()
    moved = [n for n, p in m.named_parameters()
             if p.trainable and np.abs(p.numpy()).sum() > 0
             and "lora_B" in n]
    assert moved, "training must move the adapter factors"
    for n, p in m.named_parameters():
        if not p.trainable:
            np.testing.assert_array_equal(p.numpy(), base_snap[n])
    # the hash strips the wrapper's `.base.` path segment and skips the
    # factors: training an adapter never changes the recorded base
    assert base_weights_hash(m) == base_hash


def test_lora_wrapper_grad_parity_and_adapter_restore(tmp_path):
    """The wrapper's factor gradients match the dense merged-weight
    calculus — for y = x(W + sAB): dL/dA = s*(dL/dW)Bᵀ and dL/dB =
    s*Aᵀ*(dL/dW) — and an exported adapter restores bit-identically
    into a fresh wrap via the train-side `load_adapter`."""

    class Probe(nn.Layer):
        def __init__(self, seed):
            super().__init__()
            paddle.seed(seed)
            self.qkv = nn.Linear(8, 6)

        def forward(self, x):
            return self.qkv(x)

    w = lora.LoRAWrapper(Probe(5), rank=2, targets=("qkv",))
    assert w.paths == ["qkv"]
    rng = np.random.default_rng(9)
    lyr = w.model.qkv
    lyr.lora_A._data = paddle.to_tensor(
        rng.normal(0, 0.3, (8, 2)).astype("float32"))._data
    lyr.lora_B._data = paddle.to_tensor(
        rng.normal(0, 0.3, (2, 6)).astype("float32"))._data
    assert all("lora_" in n for n, p in w.named_parameters()
               if p.trainable)
    x = paddle.to_tensor(rng.normal(0, 1, (4, 8)).astype("float32"))
    w(x).sum().backward()
    # dense oracle: a fresh layer carrying the merged weight, same loss
    dense = Probe(5)
    dense.qkv.weight._data = paddle.to_tensor(
        np.asarray(lyr.merged_weight()))._data
    dense(x).sum().backward()
    dW = dense.qkv.weight.grad
    s = lyr.scaling
    A = lyr.lora_A.numpy()
    B = lyr.lora_B.numpy()
    np.testing.assert_allclose(np.asarray(lyr.lora_A.grad),
                               s * np.asarray(dW) @ B.T, atol=1e-5)
    np.testing.assert_allclose(np.asarray(lyr.lora_B.grad),
                               s * A.T @ np.asarray(dW), atol=1e-5)
    # export -> fresh wrap -> load_adapter: bit-identical forward
    path = str(tmp_path / "probe.npz")
    w.export(path)
    w2 = lora.LoRAWrapper(Probe(5), rank=2, targets=("qkv",))
    assert w2(x).numpy().tolist() != w(x).numpy().tolist()
    w2.load(path)
    np.testing.assert_array_equal(w2(x).numpy(), w(x).numpy())
    # typed mismatch: an unwrapped model cannot restore an adapter
    with pytest.raises(InvalidArgumentError, match="no LoRALinear"):
        lora.load_adapter(Probe(5), path)
    # typed mismatch: wrong rank never half-loads
    w3 = lora.LoRAWrapper(Probe(5), rank=4, targets=("qkv",))
    with pytest.raises(InvalidArgumentError, match="rank"):
        w3.load(path)


# ---------------------------------------------------------------------------
# artifact + registry: round-trip, verification, LRU/pin lifecycle
# ---------------------------------------------------------------------------

def test_export_register_round_trip_and_typed_rejects(tmp_path, adapters):
    base = tiny_model()
    shapes = lora.attach_serving_lora(base, ("qkv",))
    sha = base_weights_hash(base)
    reg = AdapterRegistry(LoRAConfig(**LORA_CFG), shapes, base_sha=sha)
    path, file_sha = adapters["a1"]
    idx = reg.register("a1", path)
    assert idx == 1 and reg.loaded() == {"a1": 1}
    assert reg.file_sha(idx) == file_sha
    # idempotent by artifact sha: the zero-byte re-attach key
    loads_before = reg.stats()["loads"]
    assert reg.register("a1", path) == idx
    assert reg.stats()["loads"] == loads_before
    # wrong base: the artifact records the TRAINING base's hash
    reg_other = AdapterRegistry(
        LoRAConfig(**LORA_CFG), shapes, base_sha="deadbeef" * 8)
    with pytest.raises(AdapterIntegrityError, match="base"):
        reg_other.register("a1", path)
    # ...unless the serving base differs by construction (int8 etc.)
    reg_nocheck = AdapterRegistry(
        LoRAConfig(rank=4, max_adapters=3, targets=("qkv",),
                   check_base_hash=False),
        shapes, base_sha="deadbeef" * 8)
    assert reg_nocheck.register("a1", path) == 1
    # rank is baked into the compiled programs: typed mismatch
    reg_r8 = AdapterRegistry(
        LoRAConfig(rank=8, max_adapters=3, targets=("qkv",)), shapes,
        base_sha=sha)
    with pytest.raises(InvalidArgumentError, match="rank"):
        reg_r8.register("a1", path)
    # truncated artifact: typed, never garbage factors
    bad = str(tmp_path / "trunc.npz")
    with open(path, "rb") as f:
        raw = f.read()
    with open(bad, "wb") as f:
        f.write(raw[:len(raw) // 2])
    with pytest.raises(AdapterIntegrityError):
        reg.register("trunc", bad)


def test_registry_lru_eviction_pinning_and_exhaustion(adapters):
    base = tiny_model()
    shapes = lora.attach_serving_lora(base, ("qkv",))
    reg = AdapterRegistry(
        LoRAConfig(rank=4, max_adapters=2, targets=("qkv",)), shapes,
        base_sha=base_weights_hash(base))
    assert reg.resolve(None) == 0 and reg.acquire("") == 0
    i1 = reg.register("a1", adapters["a1"][0])
    i2 = reg.register("a2", adapters["a2"][0])
    pin1 = reg.acquire("a1")
    assert pin1 == i1
    # full registry: the unpinned LRU slot (a2) is evicted for a3
    i3 = reg.register("a3", adapters["a3"][0])
    assert i3 == i2 and reg.stats()["evictions"] == 1
    with pytest.raises(AdapterNotFoundError, match="a2"):
        reg.resolve("a2")
    # pin the survivor too: nothing evictable -> typed backpressure
    reg.acquire("a3")
    with pytest.raises(AdapterExhaustedError, match="pinned"):
        reg.register("a2", adapters["a2"][0])
    # release unpins; the load then succeeds (evicting LRU a1)
    reg.release(pin1)
    assert reg.register("a2", adapters["a2"][0]) == i1


def test_adapter_corrupt_fault_is_typed_and_clean_on_retry(adapters):
    """PDTPU_FAULT_ADAPTER_CORRUPT=n poisons the n-th adapter artifact
    READ (in memory — the file is untouched), so the typed reject's
    retry succeeds: the supervised re-ship path, garbage factors never
    load."""
    path, _ = adapters["a1"]
    try:
        faults.enable("adapter_corrupt", "1")
        with pytest.raises(AdapterIntegrityError):
            lora.read_adapter(path)
        header, factors, _ = lora.read_adapter(path)  # retry: clean
        assert header["rank"] == 4 and factors
    finally:
        faults.reset()


# ---------------------------------------------------------------------------
# serving engine: adapter id 0 bit-identity, mixed batches, zero compiles
# ---------------------------------------------------------------------------

def test_engine_base_bit_identity_mixed_batch_and_swap_survival(adapters):
    """The lora engine's adapter-id-0 streams are bit-identical to a
    separately built no-LoRA engine; a heterogeneous batch (base + two
    adapters on four slots IN ONE TICK) reproduces each stream's solo
    single-adapter oracle bit-for-bit; nothing compiles after warmup —
    a new adapter is a dynamic input, never a new program.  Then the
    PR-19 refresh path composes: `swap_weights` flips the BASE while
    loaded adapters survive (the factor stacks are registry state, not
    engine state) — an identity flip is bit-identical on base AND
    adapter streams, a real flip changes both streams, keeps the
    registry loaded, compiles nothing, and re-pins the registry's
    expected base so a later register() checks artifacts against the
    base actually being served."""
    from paddle_tpu.jit import state_arrays
    plain = ServingEngine(tiny_model(), **ENGINE_KW)
    eng = ServingEngine(tiny_model(), lora=LoRAConfig(**LORA_CFG),
                        **ENGINE_KW)
    plain.warmup()
    eng.warmup()
    eng.load_adapter("a1", adapters["a1"][0])
    eng.load_adapter("a2", adapters["a2"][0])
    mark = serving_compiles()
    prompts = [np.arange(1 + i, 6 + i, dtype=np.int32) for i in range(2)]
    # solo oracles: one request at a time on each engine
    want_base = [stream(plain, p, 8) for p in prompts]
    assert [stream(eng, p, 8) for p in prompts] == want_base
    solo = {name: stream(eng, prompts[0], 8, adapter=name)
            for name in ("a1", "a2")}
    assert solo["a1"] != want_base[0] and solo["a1"] != solo["a2"]
    # heterogeneous batch: all four admitted before any step
    mix = [eng.submit(prompts[0], 8, adapter="a1"),
           eng.submit(prompts[0], 8, adapter="a2"),
           eng.submit(prompts[0], 8),
           eng.submit(prompts[0], 8, adapter="a1")]
    drain(eng)
    assert mix[0].tokens(timeout=5) == solo["a1"]
    assert mix[1].tokens(timeout=5) == solo["a2"]
    assert mix[2].tokens(timeout=5) == want_base[0]
    assert mix[3].tokens(timeout=5) == solo["a1"]
    assert serving_compiles() == mark, "adapters must not compile"
    cc = eng.compile_counts()
    assert cc["total"] <= cc["bound"], cc
    # unknown adapter: typed at admission, never a hung consumer
    with pytest.raises(AdapterNotFoundError, match="ghost"):
        eng.make_request(prompts[0], 4, adapter="ghost")
    m = eng.metrics()["lora"]
    assert m["loaded"] == 2 and sorted(m["adapters"]) == ["a1", "a2"]
    plain.close()
    # -- swap survival on the SAME engine -------------------------------
    # identity flip: same seed -> same weights -> bit-identical streams
    eng.swap_weights(state_arrays(tiny_model(11)))
    assert stream(eng, prompts[0], 8, adapter="a1") == solo["a1"]
    assert stream(eng, prompts[0], 8) == want_base[0]
    # real flip: both streams move, adapters stay resident, no compile
    eng.swap_weights(state_arrays(tiny_model(7)), weights_sha="v2")
    got_base = stream(eng, prompts[0], 8)
    got_ad = stream(eng, prompts[0], 8, adapter="a1")
    assert got_base != want_base[0], "the flip must change the base"
    assert got_ad != got_base, "the adapter must act on the new base"
    m = eng.metrics()["lora"]
    assert m["loaded"] == 2 and sorted(m["adapters"]) == ["a1", "a2"]
    assert serving_compiles() == mark, "swap must not compile"
    # the registry's base pin followed the flip: an artifact trained
    # against the OLD base is now a typed reject
    with pytest.raises(AdapterIntegrityError, match="base"):
        eng.load_adapter("a3", adapters["a3"][0])
    eng.close()


@pytest.mark.slow
def test_paged_engine_mixed_adapters_parity(adapters):
    eng = ServingEngine(tiny_model(), lora=LoRAConfig(**LORA_CFG),
                        kv="paged", block_size=8, **ENGINE_KW)
    eng.warmup()
    eng.load_adapter("a1", adapters["a1"][0])
    eng.load_adapter("a2", adapters["a2"][0])
    mark = serving_compiles()
    prompt = np.arange(1, 6, dtype=np.int32)
    solo = {name: stream(eng, prompt, 12, adapter=name)
            for name in (None, "a1", "a2")}
    assert solo["a1"] != solo[None] != solo["a2"]
    mix = [eng.submit(prompt, 12, adapter=a)
           for a in (None, "a1", "a2", "a1")]
    drain(eng)
    got = [r.tokens(timeout=5) for r in mix]
    assert got == [solo[None], solo["a1"], solo["a2"], solo["a1"]]
    assert serving_compiles() == mark
    eng.close()


@pytest.mark.slow
def test_int8_base_composes_with_fp32_adapters(adapters):
    """Int8 weight-only serving bases wrap identically (the post-hook
    adds an fp32 delta on top of the int8 matmul); the training base
    hash no longer matches by construction, so check_base_hash=False is
    the documented opt-out."""
    from paddle_tpu.quantization import quantize_for_serving
    m = tiny_model()
    quantize_for_serving(m)
    eng = ServingEngine(m, lora=LoRAConfig(
        rank=4, max_adapters=3, targets=("qkv",), check_base_hash=False),
        **ENGINE_KW)
    eng.warmup()
    eng.load_adapter("a1", adapters["a1"][0])
    prompt = np.arange(1, 6, dtype=np.int32)
    base_s = stream(eng, prompt, 8)
    ad_s = stream(eng, prompt, 8, adapter="a1")
    assert base_s != ad_s, "the adapter must act on the int8 base"
    eng.close()


def test_lora_combination_rejects_name_both_knobs():
    m = tiny_model()
    draft = tiny_model(7)
    with pytest.raises(InvalidArgumentError) as ei:
        ServingEngine(m, lora=LoRAConfig(**LORA_CFG), draft_model=draft,
                      **ENGINE_KW)
    assert "lora" in str(ei.value) and "draft_model" in str(ei.value)
    with pytest.raises(InvalidArgumentError) as ei:
        ServingEngine(m, lora=LoRAConfig(**LORA_CFG), kv="paged",
                      block_size=8, prefix_cache=True, **ENGINE_KW)
    assert "lora" in str(ei.value) and "prefix_cache" in str(ei.value)
    # the PR-17 bare reject, reworded: names both knobs + the workaround
    with pytest.raises(InvalidArgumentError) as ei:
        ServingEngine(m, prefix_cache=True, **ENGINE_KW)
    msg = str(ei.value)
    assert "prefix_cache" in msg and "kv=" in msg and "paged" in msg
    # the documented PR-17 composition gap: speculative decoding and
    # prefix reuse reject typed AT CONSTRUCTION, naming both knobs —
    # never a silently-incoherent draft KV on a warm prefix hit
    with pytest.raises(InvalidArgumentError) as ei:
        ServingEngine(m, draft_model=draft, kv="paged", block_size=8,
                      prefix_cache=True, **ENGINE_KW)
    msg = str(ei.value)
    assert "prefix_cache" in msg and "draft_model" in msg


# ---------------------------------------------------------------------------
# gateway: tenant -> adapter mapping, typed unknown-adapter rejection
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_gateway_tenant_adapter_stamping_and_typed_reject(adapters):
    eng = ServingEngine(tiny_model(), lora=LoRAConfig(**LORA_CFG),
                        **ENGINE_KW)
    eng.warmup()
    eng.load_adapter("a1", adapters["a1"][0])
    prompt = np.arange(1, 6, dtype=np.int32)
    want = stream(eng, prompt, 12, adapter="a1")
    want_base = stream(eng, prompt, 12)
    gw = ServingGateway(eng, tenants={
        "acme": TenantConfig(adapter="a1"),
        "ghost-inc": TenantConfig(adapter="ghost"),
    })
    gw.start()
    try:
        assert gw.submit(prompt, 12, tenant="acme").tokens(
            timeout=60) == want
        assert gw.submit(prompt, 12).tokens(timeout=60) == want_base
        # unloaded adapter: terminal typed failure through the normal
        # admission path — never a hung consumer
        r = gw.submit(prompt, 12, tenant="ghost-inc")
        with pytest.raises(AdapterNotFoundError):
            r.tokens(timeout=60)
        assert r.done() and isinstance(r.error, AdapterNotFoundError)
        # /healthz lists the loaded adapters' artifact shas — the
        # operator's "is tenant X resident on THIS replica" answer
        status, _, payload = gw.handle("GET", "/healthz")
        assert status == 200
        hz = json.loads(payload)
        assert hz["lora"]["shas"] == {"a1": adapters["a1"][1]}
    finally:
        gw.close()
    from paddle_tpu.observability import report
    rep = report()
    assert rep["lora"]["adapters_loaded"] >= 1
    assert rep["lora"]["rejects"] >= 1


# ---------------------------------------------------------------------------
# fleet: fleet-wide hot-load (in-process + REMOTE worker), convergence
# ---------------------------------------------------------------------------

@pytest.fixture
def hard_timeout():
    def handler(signum, frame):
        raise TimeoutError("lora worker hard per-test timeout")
    old = signal.signal(signal.SIGALRM, handler)
    signal.alarm(150)
    yield
    signal.alarm(0)
    signal.signal(signal.SIGALRM, old)


def test_fleet_hot_load_remote_worker_reship_and_convergence(hard_timeout,
                                                             adapters):
    """Fleet-wide hot-load across a MIXED fleet — one in-process replica
    plus one REMOTE `--listen` worker attached over TCP: the artifact
    ships chunked + sha256-verified, `load_adapter` returns every
    replica's file sha, the adapter stream is identical from both
    replicas (the in-process engine is the oracle), a poisoned first
    read INSIDE the remote worker is re-shipped supervised, an unknown
    adapter fails the stream typed over the wire, NO replica restarts
    (hot-load is not a rollout), every health snapshot lists the
    adapter's sha, and a replica warmed AFTER the load converges onto
    the recorded adapter set.  (The same legs against a SUBPROCESS
    worker run under `slow`.)"""
    import threading
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = (_REPO + os.pathsep + env["PYTHONPATH"]
                         if env.get("PYTHONPATH") else _REPO)
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.serving.worker",
         "--listen", "127.0.0.1:0", "--index", "0"],
        stdin=subprocess.DEVNULL, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, env=env,
        start_new_session=True)
    mk = lambda: ServingEngine(tiny_model(), lora=LoRAConfig(**LORA_CFG),
                               **ENGINE_KW)
    fleet = None
    try:
        while True:  # SIGALRM guards the wait
            line = proc.stdout.readline()
            assert line, "remote worker exited before listening"
            if "worker listening on" in line:
                addr = line.strip().rsplit(" ", 1)[-1]
                break
        threading.Thread(target=lambda: proc.stdout.read(),
                         daemon=True).start()
        spec = {"model": {"factory": "paddle_tpu.serving.worker:build_gpt",
                          "kwargs": dict(GPT_KW, seed=11)},
                "engine": dict(ENGINE_KW, prefill_buckets=[8]),
                "lora": dict(LORA_CFG, targets=["qkv"])}
        fleet = FleetRouter([mk()])
        remote_rid = fleet.add_worker(spec, address=addr,
                                      boot_timeout_s=140.0)
        fleet.warmup()
        rids0 = sorted(r.id for r in fleet.manager.replicas())
        path, sha = adapters["a1"]
        got = fleet.load_adapter("a1", path)
        assert sorted(got) == rids0 and set(got.values()) == {sha}
        # the stream is replica-independent: force a request through
        # EACH replica directly and compare the adapter streams
        prompt = np.arange(1, 6, dtype=np.int32)
        want = None
        for rep in fleet.manager.replicas():
            req, resp = rep.engine.make_request(prompt, 8, adapter="a1")
            rep.engine.scheduler.submit(req, resp)
            t0 = time.monotonic()
            while not resp.done():
                fleet.step()
                assert time.monotonic() - t0 < 120
            toks = resp.tokens(timeout=5)
            assert toks
            if want is None:
                want = toks
            assert toks == want, "replicas diverged on one adapter"
        # corrupt first read INSIDE the remote worker -> typed ->
        # supervised re-ship, no restart
        rem = next(r for r in fleet.manager.replicas()
                   if r.id == remote_rid)
        rem.engine.set_fault("adapter_corrupt", "1")
        got2 = fleet.load_adapter("a2", adapters["a2"][0])
        assert set(got2.values()) == {adapters["a2"][1]}
        from paddle_tpu.observability import report
        assert report()["serving"]["adapter_ship_retries"] >= 1
        # unknown adapter: typed terminal over the wire
        requ, respu = rem.engine.make_request(prompt, 4, adapter="nope")
        rem.engine.scheduler.submit(requ, respu)
        while not respu.done():
            fleet.step()
        assert isinstance(respu.error, AdapterNotFoundError)
        assert rem.engine.post_warmup_compiles() == 0
        # hot-load is NOT a rollout: same replica set, zero restarts,
        # and every replica's health snapshot lists the adapter sha
        deadline = time.monotonic() + 30
        while True:
            fleet.step()  # status frames carry the worker's registry
            snaps = fleet.health()["replicas"]
            if all((s.get("adapters") or {}).get("a1") == sha
                   for s in snaps.values()):
                break
            assert time.monotonic() < deadline, snaps
            time.sleep(0.02)
        assert sorted(r.id for r in fleet.manager.replicas()) == rids0
        assert all(int(s.get("restarts") or 0) == 0
                   for s in snaps.values())
        # a replica warmed AFTER the load converges onto the recorded
        # adapter set — a boot must not silently drop a tenant's adapter
        fleet.add_replica(mk())
        fleet.warmup()
        for rep in fleet.manager.replicas():
            assert "a1" in rep.engine.metrics()["lora"]["adapters"]
        srv = report()["serving"]
        assert srv["adapter_loads"] >= 2 and srv["adapter_active"] >= 1
    finally:
        if fleet is not None:
            fleet.close()
        proc.kill()
        proc.wait(timeout=10)


@pytest.mark.slow
def test_subprocess_worker_hot_load_and_reship(hard_timeout, adapters):
    """One SUBPROCESS worker booted with a lora spec: load_adapter over
    the RPC pages the artifact in (sha-verified), adapter streams are
    bit-identical to an in-process lora oracle, a poisoned first read
    inside the worker is re-shipped supervised, and an unknown adapter
    fails the stream typed over the wire."""
    from paddle_tpu.serving.worker import WorkerClient
    spec = {"model": {"factory": "paddle_tpu.serving.worker:build_gpt",
                      "kwargs": dict(GPT_KW, seed=11)},
            "engine": dict(ENGINE_KW, prefill_buckets=[8]),
            "lora": dict(rank=4, max_adapters=3, targets=["qkv"])}
    wc = WorkerClient(spec, index=0, boot_timeout_s=180.0)
    try:
        while not wc.poll_ready():
            time.sleep(0.05)
        p1, sha1 = adapters["a1"]
        assert wc.load_adapter("a1", p1) == sha1
        eng = ServingEngine(tiny_model(), lora=LoRAConfig(**LORA_CFG),
                            **ENGINE_KW)
        eng.warmup()
        eng.load_adapter("a1", p1)
        prompt = np.arange(1, 6, dtype=np.int32)
        want = stream(eng, prompt, 8, adapter="a1")
        eng.close()
        req, resp = wc.make_request(prompt, 8, adapter="a1")
        wc.scheduler.submit(req, resp)
        while not resp.done():
            wc.step()
        assert resp.tokens(timeout=5) == want
        # corrupt first read INSIDE the worker -> typed -> re-ship ok
        wc.set_fault("adapter_corrupt", "1")
        assert wc.load_adapter("a2", adapters["a2"][0]) == adapters["a2"][1]
        from paddle_tpu.observability import report
        assert report()["serving"]["adapter_ship_retries"] >= 1
        # unknown adapter: typed terminal over the wire
        requ, respu = wc.make_request(prompt, 4, adapter="nope")
        wc.scheduler.submit(requ, respu)
        while not respu.done():
            wc.step()
        assert isinstance(respu.error, AdapterNotFoundError)
        assert wc.post_warmup_compiles() == 0
    finally:
        wc.close()


# ---------------------------------------------------------------------------
# probe smoke (slow tier): parity-only, tiny shapes
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_lora_probe_smoke():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "probes", "lora_probe.py"),
         "--steps", "3"],
        capture_output=True, text=True, timeout=900, env=env, cwd=_REPO)
    assert proc.returncode == 0, (proc.stderr or proc.stdout)[-800:]
    lines = [l for l in proc.stdout.splitlines() if l.startswith("LORA")]
    assert lines, proc.stdout[-400:]
    out = json.loads(lines[-1][len("LORA"):])
    assert out["smoke"] is True
    assert "failures" not in out, out.get("failures")
