"""Fast-tier guards for the eager-dispatch perf artifacts (ISSUE-2):
- probes/eager_probe.py --steps 3 smoke (the microbench can never rot),
- bench backend-probe hang fix (structured backend_unavailable, rc=0),
- GPT-2 solo-probe republish discipline."""
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_eager_probe_smoke_runs_on_cpu():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "probes", "eager_probe.py"),
         "--steps", "3", "--mlp-steps", "2"],
        capture_output=True, text=True, timeout=300, env=env, cwd=_REPO)
    assert proc.returncode == 0, proc.stderr[-800:]
    lines = [l for l in proc.stdout.splitlines() if l.startswith("EAGER")]
    assert lines, proc.stdout[-400:]
    out = json.loads(lines[-1][len("EAGER"):])
    assert out["eager_ops_per_sec"] > 0
    assert "speedup_vs_uncached" in out
    assert "parity_error" not in out, out.get("parity_error")
    assert out["legs"]["cached"]["loss"] == out["legs"]["uncached"]["loss"]


def test_backend_probe_timeout_is_structured(monkeypatch):
    """BENCH_r05 regression: an unreachable accelerator tunnel made
    `jax.default_backend()` blow the 300 s subprocess timeout and crash
    main() rc=1.  The probe must catch it and return a structured
    backend_unavailable record instead."""
    def fake_run(*a, **k):
        raise subprocess.TimeoutExpired(cmd=a[0], timeout=k.get("timeout"))

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    out = bench._probe_backend(timeout=1)
    assert out["backend_unavailable"] is True
    assert out["backend"] is None
    assert "timed out" in out["error"]


def test_backend_probe_failure_rc_is_structured(monkeypatch):
    class P:
        returncode = 1
        stdout = ""
        stderr = "RuntimeError: no backend"

    monkeypatch.setattr(bench.subprocess, "run", lambda *a, **k: P())
    out = bench._probe_backend(timeout=1)
    assert out["backend_unavailable"] is True
    assert "no backend" in out["error"]


def test_backend_probe_cpu_ok(monkeypatch):
    class P:
        returncode = 0
        stdout = "cpu\n"
        stderr = ""

    monkeypatch.setattr(bench.subprocess, "run", lambda *a, **k: P())
    out = bench._probe_backend(timeout=1)
    assert out == {"backend": "cpu", "backend_unavailable": False}


_DEGRADED_GPT2_SCRIPT = r"""
import json, os
if os.environ.get("PDTPU_IGNORE_SLOT") == "1":
    print("GPT2" + json.dumps(
        {"step_ms": 136.0, "step_ms_spread": 0.7, "mfu": 34.72,
         "slot_tf_s": 150.0}))
else:
    print("GPT2" + json.dumps({"slot_bailed": True, "slot_tf_s": 150.0}))
"""


def test_gpt2_degraded_leg_republishes_solo_probe():
    """VERDICT r4 weak #1: a slot-degraded GPT-2 run must never publish its
    measured number at the headline keys — the qualified solo-probe
    measurement is republished instead, with the degraded live leg whole
    under live_leg.unpublished_degraded_measurement."""
    out = bench._run_tpu_probe(_DEGRADED_GPT2_SCRIPT, "GPT2", timeout=60)
    solo = bench._SOLO_PROBE_PUBLISH["GPT2"]
    assert out["republished_from_solo_probe"] is True
    assert out["live_leg_slot_degraded"] is True
    assert out["mfu"] == solo["mfu"]
    assert out["step_ms"] == solo["step_ms"]
    assert out["source"] == "probes/gpt2_probe_results.txt"
    live = out["live_leg"]
    assert live["slot_degraded"] is True
    assert live["unpublished_degraded_measurement"]["step_ms"] == 136.0
    assert live["unpublished_degraded_measurement"]["mfu"] == 34.72
