"""Control-flow API (reference: fluid/layers/control_flow.py:1 While/Cond/
Switch ops) — eager tape-differentiable loops + traced lax lowering."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.static import nn as snn
from paddle_tpu.core.tensor import unwrap


def test_while_loop_eager_dynamic_trip():
    i = paddle.to_tensor(np.array(0, "int32"))
    x = paddle.to_tensor(np.array(1.0, "float32"))
    out = snn.while_loop(lambda i, x: i < 5,
                         lambda i, x: [i + 1, x * 2.0], [i, x])
    assert int(out[0]) == 5 and float(out[1]) == 32.0


def test_while_loop_eager_differentiable():
    """Dynamic-length loop differentiates through the tape (the reference's
    while_grad_op role)."""
    x = paddle.to_tensor(np.array(2.0, "float32"), stop_gradient=False)
    i = paddle.to_tensor(np.array(0, "int32"))
    # x -> x^(2^3) = x^8; dy/dx = 8 x^7
    out = snn.while_loop(lambda i, y: i < 3,
                         lambda i, y: [i + 1, y * y], [i, x])
    out[1].backward()
    np.testing.assert_allclose(float(x.grad), 8 * 2.0 ** 7, rtol=1e-5)


def test_while_loop_traced_in_jit():
    @paddle.jit.to_static
    def collatz_steps(n):
        i = paddle.zeros([], "int32")
        out = snn.while_loop(
            lambda n, i: n > 1,
            lambda n, i: [snn.cond((n % 2) == 0, lambda: n // 2,
                                   lambda: 3 * n + 1), i + 1],
            [n, i])
        return out[1]
    got = collatz_steps(paddle.to_tensor(np.array(6, "int32")))
    assert int(got) == 8  # 6→3→10→5→16→8→4→2→1


def test_cond_eager_and_grad():
    x = paddle.to_tensor(np.array(3.0, "float32"), stop_gradient=False)
    y = snn.cond(x > 0, lambda: x * 2.0, lambda: x * -1.0)
    y.backward()
    assert float(y) == 6.0 and float(x.grad) == 2.0


def test_cond_traced_grad():
    from paddle_tpu.core.tensor import Tensor

    def f(xv):
        x = Tensor(xv)
        y = snn.cond(x > 0, lambda: unwrap(x) * 2.0, lambda: unwrap(x) * -1.0)
        return unwrap(y)
    g = jax.grad(f)(jnp.float32(3.0))
    assert float(g) == 2.0
    g = jax.grad(f)(jnp.float32(-3.0))
    assert float(g) == -1.0


def test_case_eager_first_true_wins_and_default():
    x = paddle.to_tensor(np.array(0.2, "float32"))
    r = snn.case([(x > 0.5, lambda: paddle.to_tensor(1.0)),
                  (x > 0.1, lambda: paddle.to_tensor(2.0))],
                 default=lambda: paddle.to_tensor(3.0))
    assert float(r) == 2.0
    r = snn.case([(x > 0.5, lambda: paddle.to_tensor(1.0)),
                  (x > 0.4, lambda: paddle.to_tensor(2.0))])
    assert float(r) == 2.0  # no default: last branch runs


def test_case_traced():
    @paddle.jit.to_static
    def f(x):
        return snn.case([(x > 0.5, lambda: x * 1.0),
                         (x > 0.1, lambda: x * 10.0)],
                        default=lambda: x * 100.0)
    assert float(f(paddle.to_tensor(np.array(0.3, "float32")))) == \
        pytest.approx(3.0)
    assert float(f(paddle.to_tensor(np.array(0.05, "float32")))) == \
        pytest.approx(5.0)


def test_switch_case_eager_and_traced():
    def mk(i):
        return snn.switch_case(
            paddle.to_tensor(np.array(i, "int32")),
            {1: lambda: paddle.to_tensor(10.0),
             3: lambda: paddle.to_tensor(30.0)},
            default=lambda: paddle.to_tensor(-1.0))
    assert float(mk(1)) == 10.0 and float(mk(3)) == 30.0
    assert float(mk(2)) == -1.0

    @paddle.jit.to_static
    def f(i):
        return snn.switch_case(i, {1: lambda: paddle.to_tensor(10.0),
                                   3: lambda: paddle.to_tensor(30.0)},
                               default=lambda: paddle.to_tensor(-1.0))
    assert float(f(paddle.to_tensor(np.array(3, "int32")))) == 30.0
    assert float(f(paddle.to_tensor(np.array(7, "int32")))) == -1.0


def test_while_loop_rnn_style_dynamic_length():
    """Dynamic-length sequence sum via while_loop (the LoD-free RNN
    pattern the reference's While op enables)."""
    seq = paddle.to_tensor(np.arange(10, dtype="float32"))
    n = paddle.to_tensor(np.array(7, "int32"))  # runtime length
    i = paddle.to_tensor(np.array(0, "int32"))
    acc = paddle.to_tensor(np.array(0.0, "float32"))

    out = snn.while_loop(
        lambda i, acc: i < n,
        lambda i, acc: [i + 1, acc + seq[i]], [i, acc])
    assert float(out[1]) == float(np.arange(7).sum())


def test_while_loop_validations():
    with pytest.raises(ValueError):
        snn.while_loop(lambda x: paddle.to_tensor(np.ones((2,), "bool")),
                       lambda x: [x], [paddle.to_tensor(1.0)])
    with pytest.raises(ValueError):
        snn.while_loop(lambda x: x < 1, lambda x: [x], [])
