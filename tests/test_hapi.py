"""hapi Model.fit end-to-end (reference: tests/book/test_recognize_digits.py
pattern — train a small model until the loss drops, with metrics, eval,
checkpoint round-trip)."""
import os

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.vision import models, datasets


class _SepDataset(paddle.io.Dataset):
    """Linearly separable 2-class image blobs — learnable in a few steps."""

    def __init__(self, n=64):
        rng = np.random.RandomState(0)
        self.labels = rng.randint(0, 2, (n,)).astype("int64")
        base = np.where(self.labels[:, None, None, None] > 0, 0.8, -0.8)
        self.images = (base + 0.1 * rng.randn(n, 1, 28, 28)).astype("float32")

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, i):
        return self.images[i], self.labels[i]


def test_model_fit_eval_predict(tmp_path):
    net = models.LeNet(num_classes=2)
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.Adam(learning_rate=1e-3,
                                        parameters=net.parameters()),
        loss=paddle.nn.CrossEntropyLoss(),
        metrics=paddle.metric.Accuracy())
    ds = _SepDataset(64)
    losses = []

    class Recorder(paddle.hapi.callbacks.Callback):
        def on_train_batch_end(self, step, logs=None):
            losses.append(logs["loss"])

    model.fit(ds, epochs=2, batch_size=16, verbose=0,
              callbacks=[Recorder()])
    assert np.mean(losses[-4:]) < np.mean(losses[:4])

    ev = model.evaluate(ds, batch_size=16, verbose=0)
    assert ev["eval_acc"] > 0.9

    preds = model.predict(ds, batch_size=16, stack_outputs=True)
    assert preds[0].shape == (64, 2)

    path = str(tmp_path / "ckpt" / "final")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    model.save(path)
    net2 = models.LeNet(num_classes=2)
    model2 = paddle.Model(net2)
    model2.prepare(
        optimizer=paddle.optimizer.Adam(parameters=net2.parameters()),
        loss=paddle.nn.CrossEntropyLoss())
    model2.load(path)
    x = paddle.to_tensor(ds.images[:4])
    net.eval(); net2.eval()
    np.testing.assert_allclose(net(x).numpy(), net2(x).numpy(),
                               rtol=1e-5, atol=1e-5)


def test_model_summary():
    net = models.LeNet()
    info = paddle.summary(net)
    assert info["total_params"] > 0
    assert info["total_params"] == info["trainable_params"]


def test_callbacks_early_stopping():
    net = models.LeNet(num_classes=2)
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.Adam(learning_rate=0.0,
                                        parameters=net.parameters()),
        loss=paddle.nn.CrossEntropyLoss())
    ds = _SepDataset(32)
    es = paddle.hapi.callbacks.EarlyStopping(monitor="eval_loss", patience=0,
                                             verbose=0)
    model.fit(ds, eval_data=ds, epochs=5, batch_size=16, verbose=0,
              callbacks=[es])
    # lr=0 -> no improvement -> stops well before 5 epochs
    assert model.stop_training
