"""Fluid-era layers + nn.utils (reference: fluid/layers/nn.py hsigmoid/
nce/row_conv/pool2d/ctc_greedy_decoder/clip_by_norm, control_flow.py
StaticRNN, dygraph weight_norm_hook)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
import paddle_tpu.nn.functional as F


def test_hsigmoid_layer_trains():
    paddle.seed(0)
    rng = np.random.RandomState(0)
    h = nn.HSigmoidLoss(8, 10)
    x = paddle.to_tensor(rng.randn(4, 8).astype("float32"),
                         stop_gradient=False)
    lab = paddle.to_tensor(rng.randint(0, 10, (4,)).astype("int64"))
    loss = h(x, lab).sum()
    loss.backward()
    assert np.abs(x.grad.numpy()).sum() > 0
    assert h.weight.grad is not None


def test_nce_loss_shape_and_grad():
    paddle.seed(0)
    rng = np.random.RandomState(1)
    n = nn.NCELoss(8, 50, num_neg_samples=5, seed=1)
    x = paddle.to_tensor(rng.randn(4, 8).astype("float32"),
                         stop_gradient=False)
    lab = paddle.to_tensor(rng.randint(0, 50, (4,)).astype("int64"))
    loss = n(x, lab)
    assert list(loss.shape) == [4, 1]
    loss.sum().backward()
    assert np.isfinite(x.grad.numpy()).all()
    assert n.weight.grad is not None


def test_row_conv_lookahead_semantics():
    rng = np.random.RandomState(2)
    rc = nn.RowConv(3, 1)
    # w[0]=0 (current), w[1]=1 (next step): out[t] == x[t+1], zero-pad end
    rc.weight._set_data(np.array([[0, 0, 0], [1, 1, 1]], "float32"))
    xs = paddle.to_tensor(rng.randn(1, 5, 3).astype("float32"))
    out = rc(xs).numpy()
    np.testing.assert_allclose(out[0, :4], xs.numpy()[0, 1:], rtol=1e-5)
    np.testing.assert_allclose(out[0, 4], 0.0, atol=1e-6)


def test_pool2d_layer_and_static_rnn():
    rng = np.random.RandomState(3)
    p2 = nn.Pool2D(pool_size=2, pool_type="avg", pool_stride=2)
    img = paddle.to_tensor(rng.randn(1, 2, 4, 4).astype("float32"))
    assert list(p2(img).shape) == [1, 2, 2, 2]

    srnn = nn.StaticRNN()
    seq = paddle.to_tensor(np.ones((4, 2, 3), "float32"))
    srnn.step_input(seq)
    srnn.memory(paddle.to_tensor(np.zeros((2, 3), "float32")))

    def body(ins, mems):
        s = mems[0] + ins[0]
        return s, [s]

    outs, final = srnn.run(body)
    assert list(outs.shape) == [4, 2, 3]
    np.testing.assert_allclose(outs.numpy()[-1], 4.0)
    np.testing.assert_allclose(final[0].numpy(), 4.0)


def test_ctc_greedy_decoder_and_clip_by_norm():
    probs = np.zeros((1, 6, 4), "float32")
    for t, c in enumerate([1, 1, 3, 2, 3, 3]):  # blank=3
        probs[0, t, c] = 1.0
    dec, lens = F.ctc_greedy_decoder(paddle.to_tensor(probs), blank=3)
    assert dec.numpy()[0][:int(lens.numpy()[0])].tolist() == [1, 2]

    v = paddle.to_tensor(np.array([3.0, 4.0], "float32"))
    np.testing.assert_allclose(
        np.linalg.norm(F.clip_by_norm(v, 1.0).numpy()), 1.0, rtol=1e-5)
    # below the cap: unchanged
    np.testing.assert_allclose(F.clip_by_norm(v, 10.0).numpy(), v.numpy())


def test_weight_norm_roundtrip_and_grads():
    paddle.seed(0)
    rng = np.random.RandomState(4)
    lin = nn.Linear(4, 3)
    xin = paddle.to_tensor(rng.randn(2, 4).astype("float32"))
    base = lin(xin).numpy()

    nn.utils.weight_norm(lin, "weight", dim=0)
    # reparameterization preserves the function
    np.testing.assert_allclose(lin(xin).numpy(), base, rtol=1e-4,
                               atol=1e-5)
    names = [n for n, _ in lin.named_parameters()]
    assert any("weight_g" in n for n in names)
    assert any("weight_v" in n for n in names)
    # reference norm_except_dim layout: g is 1-D [d], not keepdims —
    # state_dicts interchange with reference weight-normed checkpoints
    assert list(lin.weight_g.shape) == [lin.weight_v.shape[0]]
    assert not any(n.endswith(".weight") or n == "weight" for n in names)
    lin(xin).sum().backward()
    assert lin.weight_g.grad is not None and lin.weight_v.grad is not None

    nn.utils.remove_weight_norm(lin, "weight")
    np.testing.assert_allclose(lin(xin).numpy(), base, rtol=1e-4,
                               atol=1e-5)
    names = [n for n, _ in lin.named_parameters()]
    assert not any("weight_g" in n for n in names)


def test_spectral_norm_util_unit_sigma():
    paddle.seed(0)
    lin = nn.Linear(6, 6)
    nn.utils.spectral_norm(lin, "weight", n_power_iterations=3)
    for _ in range(5):  # power iteration refines u across forwards
        lin(paddle.to_tensor(np.random.RandomState(5)
                             .randn(1, 6).astype("float32")))
    s = np.linalg.svd(lin.weight.numpy(), compute_uv=False)
    assert abs(s[0] - 1.0) < 0.05, s[0]
