"""Round-4 fluid.layers long-tail: matrix_nms vs a numpy oracle, the
RCNN/RetinaNet/EAST stragglers, seq2seq helper family, and spot oracles
for the layers_extra ops.  Reference: fluid/layers/detection.py:3544
(matrix_nms_op), :311 (rpn_target_assign), :2594
(generate_proposal_labels), rnn.py helper family, nn.py/loss.py tails."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.fluid import layers as fl
from paddle_tpu.vision import ops, rcnn_ops


def _np_matrix_nms(boxes, scores, score_thresh, topn, use_gaussian, sigma):
    """Per-class decayed scores, numpy oracle of matrix_nms_op."""
    out = {}
    for c in range(scores.shape[0]):
        s = scores[c]
        keep = np.nonzero(s >= score_thresh)[0]
        keep = keep[np.argsort(-s[keep], kind="stable")][:topn]
        if len(keep) == 0:
            out[c] = ([], [])
            continue
        b = boxes[keep]
        ious = np.zeros((len(keep), len(keep)))
        for i in range(len(keep)):
            for j in range(len(keep)):
                x1 = max(b[i, 0], b[j, 0]); y1 = max(b[i, 1], b[j, 1])
                x2 = min(b[i, 2], b[j, 2]); y2 = min(b[i, 3], b[j, 3])
                inter = max(x2 - x1, 0) * max(y2 - y1, 0)
                a1 = (b[i, 2] - b[i, 0]) * (b[i, 3] - b[i, 1])
                a2 = (b[j, 2] - b[j, 0]) * (b[j, 3] - b[j, 1])
                ious[i, j] = inter / max(a1 + a2 - inter, 1e-10)
        ds = []
        for i in range(len(keep)):
            min_decay = 1.0
            for j in range(i):
                max_iou_j = max([ious[k, j] for k in range(j)] or [0.0])
                iou = ious[j, i]
                if use_gaussian:
                    decay = np.exp((max_iou_j ** 2 - iou ** 2) * sigma)
                else:
                    decay = (1 - iou) / max(1 - max_iou_j, 1e-10)
                min_decay = min(min_decay, decay)
            ds.append(s[keep[i]] * min_decay)
        out[c] = (keep, ds)
    return out


@pytest.mark.parametrize("use_gaussian", [False, True])
def test_matrix_nms_oracle(use_gaussian):
    rng = np.random.RandomState(0)
    m, c = 8, 3
    boxes = np.sort(rng.rand(m, 4).astype("float32") * 10, axis=1)[None]
    scores = rng.rand(1, c, m).astype("float32")
    rows, counts = ops.matrix_nms(
        paddle.to_tensor(boxes), paddle.to_tensor(scores),
        score_threshold=0.2, post_threshold=0.0, nms_top_k=8,
        keep_top_k=10, use_gaussian=use_gaussian, gaussian_sigma=2.0,
        background_label=0)
    oracle = _np_matrix_nms(boxes[0], scores[0], 0.2, 8, use_gaussian, 2.0)
    want = []
    for cc in (1, 2):  # background_label 0 excluded
        keep, ds = oracle[cc]
        want += [(cc, d, k) for d, k in zip(ds, keep)]
    want.sort(key=lambda t: -t[1])
    got = rows.numpy()[0]
    n = int(counts.numpy()[0])
    assert n == min(len(want), 10)  # keep_top_k caps the output
    for i, (cc, d, k) in enumerate(want[:n]):
        assert got[i, 0] == cc
        np.testing.assert_allclose(got[i, 1], d, rtol=1e-4)
        np.testing.assert_allclose(got[i, 2:], boxes[0, k], rtol=1e-5)
    assert (got[n:] == -1).all()


def test_rpn_target_assign_samples_and_gathers():
    rng = np.random.RandomState(0)
    a = 16
    anchors = np.zeros((a, 4), "float32")
    for i in range(a):
        x, y = (i % 4) * 8, (i // 4) * 8
        anchors[i] = [x, y, x + 10, y + 10]
    gt = np.array([[[0, 0, 10, 10], [17, 17, 26, 26]]], "float32")
    bbox_pred = paddle.to_tensor(rng.randn(1, a, 4).astype("float32"),
                                 stop_gradient=False)
    cls_logits = paddle.to_tensor(rng.randn(1, a, 1).astype("float32"),
                                  stop_gradient=False)
    scores, loc, labels, tgt, w_in = rcnn_ops.rpn_target_assign(
        bbox_pred, cls_logits, paddle.to_tensor(anchors), None,
        paddle.to_tensor(gt), im_info=paddle.to_tensor(
            np.array([[32.0, 32.0, 1.0]], "float32")),
        rpn_batch_size_per_im=8, rpn_positive_overlap=0.7,
        rpn_negative_overlap=0.3, use_random=False)
    lab = labels.numpy().reshape(-1)
    n_fg = int((lab == 1).sum())
    assert n_fg >= 2  # each gt's best anchor is fg
    assert loc.shape[0] == n_fg and tgt.shape[0] == n_fg
    assert scores.shape[0] == len(lab)
    # grads flow through the prediction gathers
    (scores.sum() + loc.sum()).backward()
    assert np.abs(cls_logits.grad.numpy()).sum() > 0
    assert np.abs(bbox_pred.grad.numpy()).sum() > 0


def test_generate_proposal_labels_contract():
    rng = np.random.RandomState(1)
    rois = np.sort(rng.rand(30, 4).astype("float32") * 30, axis=1)
    gt = np.array([[[2, 2, 12, 12], [15, 15, 28, 28]]], "float32")
    cls = np.array([[3, 7]], "int32")
    out = rcnn_ops.generate_proposal_labels(
        paddle.to_tensor(rois), paddle.to_tensor(cls), None,
        paddle.to_tensor(gt), batch_size_per_im=16, fg_fraction=0.5,
        fg_thresh=0.5, bg_thresh_hi=0.5, bg_thresh_lo=0.0,
        class_nums=10, use_random=False)
    s_rois, labels, tgts, w_in, w_out, nums = out
    n = int(nums.numpy()[0])
    assert s_rois.shape[0] == n == labels.shape[0]
    lab = labels.numpy()
    assert set(np.unique(lab)).issubset({0, 3, 7})
    # fg rows put their targets in the 4*label slot
    for i in range(n):
        if lab[i] > 0:
            c = int(lab[i])
            assert np.abs(w_in.numpy()[i, 4 * c:4 * c + 4] - 1).sum() == 0


def test_polygon_box_transform_oracle():
    rng = np.random.RandomState(2)
    x = rng.randn(1, 4, 2, 3).astype("float32")
    out = rcnn_ops.polygon_box_transform(paddle.to_tensor(x)).numpy()
    for cch in range(4):
        for h in range(2):
            for w in range(3):
                want = (w * 4 - x[0, cch, h, w] if cch % 2 == 0
                        else h * 4 - x[0, cch, h, w])
                np.testing.assert_allclose(out[0, cch, h, w], want,
                                           rtol=1e-6)


def test_roi_perspective_transform_identity():
    # an axis-aligned quad equal to the target rectangle = plain crop
    rng = np.random.RandomState(3)
    feat = rng.randn(1, 2, 8, 8).astype("float32")
    quad = np.array([[1, 1, 4, 1, 4, 4, 1, 4]], "float32")  # tl tr br bl
    x = paddle.to_tensor(feat, stop_gradient=False)
    out, mask, mats = rcnn_ops.roi_perspective_transform(x, quad, 4, 4)
    np.testing.assert_allclose(out.numpy()[0, :, 0, 0], feat[0, :, 1, 1],
                               rtol=1e-4)
    np.testing.assert_allclose(out.numpy()[0, :, 3, 3], feat[0, :, 4, 4],
                               rtol=1e-4)
    assert mask.numpy().all()
    out.sum().backward()
    assert np.abs(x.grad.numpy()).sum() > 0


def test_box_decoder_and_assign_picks_argmax_class():
    prior = np.array([[0, 0, 10, 10]], "float32")
    var = np.full((1, 4), 0.1, "float32")
    deltas = np.zeros((1, 8), "float32")
    deltas[0, 4:] = [1.0, 0.0, 0.0, 0.0]  # class-1 box shifted in x
    score = np.array([[0.2, 0.8]], "float32")
    dec, assigned = rcnn_ops.box_decoder_and_assign(
        paddle.to_tensor(prior), paddle.to_tensor(var),
        paddle.to_tensor(deltas), paddle.to_tensor(score), box_clip=4.0)
    assert list(dec.shape) == [1, 8]
    np.testing.assert_allclose(assigned.numpy(), dec.numpy()[:, 4:8])


@pytest.mark.slow
def test_seq2seq_helper_family():
    import paddle_tpu.nn as nn
    from paddle_tpu.nn.decode import (BasicDecoder, TrainingHelper,
                                      GreedyEmbeddingHelper, dynamic_decode)
    paddle.seed(0)
    rng = np.random.RandomState(0)
    cell = nn.GRUCell(4, 4)
    proj = nn.Linear(4, 6)
    inputs = paddle.to_tensor(rng.randn(2, 5, 4).astype("float32"))
    helper = TrainingHelper(inputs, sequence_length=paddle.to_tensor(
        np.array([5, 3], "int64")))
    dec = BasicDecoder(cell, helper,
                       initial_states=paddle.to_tensor(
                           np.zeros((2, 4), "float32")),
                       output_fn=proj)
    outs, states = dynamic_decode(dec, max_step_num=5)
    # batch-major contract: (B, T, vocab)
    assert list(outs.cell_outputs.shape) == [2, 5, 6]
    assert list(outs.sample_ids.shape) == [2, 5]
    # greedy embedding helper runs a short free decode
    emb = nn.Embedding(6, 4)
    helper2 = GreedyEmbeddingHelper(lambda ids: emb(ids),
                                    paddle.to_tensor(
                                        np.zeros(2, "int64")), end_token=5)
    dec2 = BasicDecoder(cell, helper2,
                        initial_states=paddle.to_tensor(
                            np.zeros((2, 4), "float32")),
                        output_fn=proj)
    outs2, _ = dynamic_decode(dec2, max_step_num=4)
    assert np.asarray(outs2.sample_ids.numpy()).ndim == 2


def test_beam_search_step_and_decode():
    from paddle_tpu.nn.decode import beam_search
    b, k, v = 1, 2, 5
    pre_ids = paddle.to_tensor(np.array([[1], [2]], "int64"))
    pre_scores = paddle.to_tensor(np.array([[0.0], [-1.0]], "float32"))
    scores = paddle.to_tensor(np.log(np.array(
        [[.05, .05, .6, .2, .1], [.1, .1, .2, .3, .3]], "float32")))
    ids, sc, parent = beam_search(pre_ids, pre_scores, None, scores,
                                  beam_size=k, end_id=0,
                                  return_parent_idx=True)
    assert list(ids.shape) == [2, 1]
    # best expansion is beam 0 token 2
    assert int(ids.numpy()[0, 0]) == 2 and int(parent.numpy()[0]) == 0


def test_beam_search_decode_multibatch_backtrack():
    """Regression (r4 review): flat parent rows from beam_search must be
    reduced to per-batch beam slots before gather_tree, and scores must be
    backtracked through the same ancestry — batch element 1 exposes both."""
    from paddle_tpu.nn.decode import beam_search_decode
    k = 2
    # T=2, B=2: at t=1 batch 1's lanes BOTH come from its beam 1 (flat
    # parent rows 3, 3); batch 0 keeps identity parents (rows 0, 1)
    ids = np.array([[10, 11, 20, 21], [12, 13, 22, 23]], "int64")
    parents = np.array([[0, 1, 2, 3], [0, 1, 3, 3]], "int64")
    scores = np.array([[0.1, 0.2, 0.3, 0.4], [0.5, 0.6, 0.7, 0.8]],
                      "float32")
    full, sc = beam_search_decode(
        paddle.to_tensor(ids), paddle.to_tensor(scores), beam_size=k,
        end_id=0, parents=paddle.to_tensor(parents))
    fv, sv = full.numpy(), sc.numpy()
    # batch 1 lane 0 ancestry: t=1 token 22 came from beam 1 -> t=0 is 21
    assert fv[0, 1, 0] == 21 and fv[1, 1, 0] == 22
    np.testing.assert_allclose(sv[0, 1, 0], 0.4)  # t=0 score of beam 1
    # batch 0 is identity — untouched
    assert fv[0, 0].tolist() == [10, 11]


def test_layers_extra_spot_oracles():
    rng = np.random.RandomState(4)
    # lrn matches a direct numpy evaluation
    x = rng.rand(1, 6, 2, 2).astype("float32")
    got = fl.lrn(paddle.to_tensor(x), n=3, k=1.0, alpha=0.1,
                 beta=0.75).numpy()
    sq = x ** 2
    for c in range(6):
        lo, hi = max(0, c - 1), min(6, c + 2)
        acc = sq[:, lo:hi].sum(axis=1)
        np.testing.assert_allclose(
            got[:, c], x[:, c] / (1.0 + 0.1 * acc) ** 0.75, rtol=1e-4)
    # huber
    h = fl.huber_loss(paddle.to_tensor(np.array([0.0, 3.0], "float32")),
                      paddle.to_tensor(np.array([0.5, 0.0], "float32")),
                      delta=1.0).numpy()
    np.testing.assert_allclose(h, [0.125, 2.5], rtol=1e-6)
    # edit distance
    d, num = fl.edit_distance(
        paddle.to_tensor(np.array([[1, 2, 3]], "int64")),
        paddle.to_tensor(np.array([[1, 3, 3]], "int64")), normalized=False)
    assert float(d.numpy()[0, 0]) == 1.0
    # hash is deterministic and in range
    hh = fl.hash(paddle.to_tensor(np.array([[7], [7]], "int64")),
                 hash_size=100, num_hash=2).numpy()
    assert (hh >= 0).all() and (hh < 100).all()
    assert (hh[0] == hh[1]).all()
    # mul flattens
    m = fl.mul(paddle.to_tensor(rng.randn(2, 3, 4).astype("float32")),
               paddle.to_tensor(rng.randn(12, 5).astype("float32")),
               x_num_col_dims=1).numpy()
    assert m.shape == (2, 5)
    # sequence_conv context window
    w = rng.randn(3 * 4, 2).astype("float32")
    sx = rng.randn(1, 5, 4).astype("float32")
    sc = fl.sequence_conv(paddle.to_tensor(sx), 2, filter_size=3,
                          weight=paddle.to_tensor(w)).numpy()
    pad = np.pad(sx, [(0, 0), (1, 1), (0, 0)])
    cols = np.concatenate([pad[:, 0:5], pad[:, 1:6], pad[:, 2:7]], -1)
    np.testing.assert_allclose(sc, cols @ w, rtol=1e-4, atol=1e-5)
    # program-region constructs fail loudly with guidance
    for ctor in (fl.While, fl.Switch, fl.IfElse, fl.DynamicRNN):
        with pytest.raises(NotImplementedError):
            ctor(None)


def test_retinanet_detection_output_and_lanms():
    rng = np.random.RandomState(5)
    # one FPN level, 6 anchors; deltas zero -> decoded == anchors
    anchors = np.stack([np.array([i * 10, i * 10, i * 10 + 8, i * 10 + 8],
                                 "float32") for i in range(6)])
    deltas = np.zeros((1, 6, 4), "float32")
    scores = np.zeros((1, 6, 3), "float32")
    scores[0, 1, 2] = 0.9
    scores[0, 4, 0] = 0.7
    out, counts = rcnn_ops.retinanet_detection_output(
        [paddle.to_tensor(deltas)], [paddle.to_tensor(scores)],
        [paddle.to_tensor(anchors)], score_threshold=0.5, keep_top_k=4)
    on = out.numpy()
    n = int(counts.numpy()[0])
    assert n == 2
    # top row: class 2 score 0.9 at anchor 1's box
    assert on[0, 0, 0] == 2 and abs(on[0, 0, 1] - 0.9) < 1e-5
    np.testing.assert_allclose(on[0, 0, 2:], anchors[1], rtol=1e-5)

    # locality-aware NMS merges the two overlapping consecutive boxes
    bb = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]],
                  "float32")
    sc = np.zeros((2, 3), "float32")
    sc[1] = [0.8, 0.4, 0.9]
    rows, cnt = rcnn_ops.locality_aware_nms(
        paddle.to_tensor(bb), paddle.to_tensor(sc), score_threshold=0.1,
        nms_top_k=10, keep_top_k=5, nms_threshold=0.5)
    got = rows.numpy()
    assert cnt == 2
    # merged box = score-weighted average of the first two
    w0, w1 = 0.8, 0.4
    merged = (bb[0] * w0 + bb[1] * w1) / (w0 + w1)
    row = got[got[:, 1] > 0.99][0]   # accumulated score clipped to 1.0
    np.testing.assert_allclose(row[2:], merged, rtol=1e-5)


def test_generate_mask_labels_rasterizes():
    # one image, one fg roi matched to a square polygon instance
    rois = np.array([[0.0, 0.0, 8.0, 8.0]], "float32")
    labels = np.array([2], "int32")
    # square covering the left half of the roi
    segms = [[[[0.0, 0.0, 4.0, 0.0, 4.0, 8.0, 0.0, 8.0]]]][0]
    mask_rois, has_mask, masks = rcnn_ops.generate_mask_labels(
        None, None, None, [segms], paddle.to_tensor(rois),
        paddle.to_tensor(labels), num_classes=4, resolution=8)
    assert list(mask_rois.shape) == [1, 4]
    assert has_mask.numpy().tolist() == [1]
    m = masks.numpy().reshape(1, 4, 8, 8)
    assert (m[0, 0] == -1).all() and (m[0, 1] == -1).all()
    cls2 = m[0, 2]
    # left half of the 8x8 grid covered, right half empty
    assert cls2[:, :4].mean() == 1.0 and cls2[:, 4:].mean() == 0.0


def test_incubate_auto_checkpoint_env_contract(tmp_path, monkeypatch):
    """reference acp env contract (auto_checkpoint.py:598): OFF -> plain
    range + warning; EDL env ON -> completed epochs skipped on resume."""
    import warnings
    from paddle_tpu import incubate
    from paddle_tpu.incubate.checkpoint import auto_checkpoint as acp
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert list(incubate.checkpoint.train_epoch_range(3)) == [0, 1, 2]
        assert any("auto checkpoint is OFF" in str(x.message) for x in w)
    monkeypatch.setenv(acp.CONST_ACP_ENV, acp.CONST_ACP_VALUE)
    monkeypatch.setenv(acp.CONST_CHECKPOINT_PATH, str(tmp_path))
    monkeypatch.setenv(acp.CONST_JOB_ID, "job0")
    mgr = acp._env_manager()
    seen = []
    for e in incubate.checkpoint.train_epoch_range(3):
        seen.append(e)
        mgr.save({"w": paddle.to_tensor(np.ones(2, "float32"))._data},
                 step=e, extra_meta={"epoch": e})
        if e == 1:
            break  # simulate preemption after epoch 1's checkpoint
    assert seen == [0, 1]
    assert list(incubate.checkpoint.train_epoch_range(3)) == [2]
