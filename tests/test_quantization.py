"""Quantization: QAT fake-quant + PTQ int8 conversion.

Reference test strategy: slim's test_imperative_qat.py trains a small conv
net with ImperativeQuantAware and checks the quantized model tracks fp32
accuracy; test_post_training_quantization_* calibrate then compare."""
import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.quantization import (
    fake_quantize_dequantize_abs_max,
    fake_quantize_dequantize_channel_wise_abs_max,
    ImperativeQuantAware, PostTrainingQuantization, Int8Linear,
    QuantedLinear, QuantedConv2D)


def test_fake_qdq_values_on_grid_and_ste_grad():
    x = paddle.to_tensor(np.linspace(-2, 2, 31).astype("float32"))
    x.stop_gradient = False
    y = fake_quantize_dequantize_abs_max(x, bits=8)
    # quantized values live on the 8-bit grid scaled by absmax
    step = 2.0 / 127
    np.testing.assert_allclose(y.numpy() / step,
                               np.round(y.numpy() / step), atol=1e-5)
    np.testing.assert_allclose(y.numpy(), x.numpy(), atol=step)
    # STE: gradient is identity
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), np.ones(31, "float32"),
                               atol=1e-6)


def test_channel_wise_scales():
    w = paddle.to_tensor(
        (np.random.RandomState(0).randn(4, 8) *
         np.array([0.1, 1.0, 10.0, 100.0])[:, None]).astype("float32"))
    q = fake_quantize_dequantize_channel_wise_abs_max(w, quant_axis=0)
    # each row keeps ~8-bit relative resolution despite 1000x range spread
    rel = np.abs(q.numpy() - w.numpy()) / np.abs(w.numpy()).max(1, keepdims=True)
    assert rel.max() < 1.0 / 127


def _blob_data(n=512, d=16, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    teacher = rng.randn(d, classes).astype("float32")
    x = rng.randn(n, d).astype("float32")
    y = (x @ teacher).argmax(1).astype("int64")
    return x, y


def _mlp(d=16, classes=4):
    return paddle.nn.Sequential(
        paddle.nn.Linear(d, 32), paddle.nn.ReLU(),
        paddle.nn.Linear(32, classes))


def _train(model, x, y, steps=60, lr=5e-2):
    opt = paddle.optimizer.Adam(learning_rate=lr,
                                parameters=model.parameters())
    crit = paddle.nn.CrossEntropyLoss()
    xt, yt = paddle.to_tensor(x), paddle.to_tensor(y)
    for _ in range(steps):
        loss = crit(model(xt), yt)
        loss.backward()
        opt.step()
        opt.clear_grad()
    return float(loss)


def _acc(model, x, y):
    model.eval()
    logits = model(paddle.to_tensor(x)).numpy()
    return float((logits.argmax(1) == y).mean())


def test_qat_trains_and_tracks_fp32_accuracy():
    x, y = _blob_data()
    paddle.seed(0)
    model = _mlp()
    _train(model, x, y, steps=40)
    fp32_acc = _acc(model, x, y)

    qat = ImperativeQuantAware()
    qmodel = qat.quantize(model)
    assert isinstance(qmodel[0], QuantedLinear)  # swapped in place
    qmodel.train()
    _train(qmodel, x, y, steps=30)  # finetune with fake quant in the graph
    q_acc = _acc(qmodel, x, y)
    assert q_acc >= fp32_acc - 0.01, (fp32_acc, q_acc)
    # observers populated
    assert float(qmodel[0].act_scale.numpy()) > 0


def test_qat_save_quantized_model_roundtrip(tmp_path):
    x, y = _blob_data(n=64)
    paddle.seed(1)
    model = _mlp()
    qat = ImperativeQuantAware()
    qmodel = qat.quantize(model)
    qmodel.train()
    _train(qmodel, x, y, steps=5)
    qmodel.eval()
    ref = qmodel(paddle.to_tensor(x)).numpy()
    path = str(tmp_path / "qat_model")
    qat.save_quantized_model(
        qmodel, path,
        input_spec=[paddle.static.InputSpec([64, 16], "float32")])
    loaded = paddle.jit.load(path)
    out = loaded(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_ptq_lenet_within_one_percent():
    """PTQ'd conv net must stay within 1% of the fp32 accuracy
    (VERDICT r1 'done' bar for quantization)."""
    from paddle_tpu.vision.models import LeNet
    rng = np.random.RandomState(0)
    n, classes = 256, 10
    # synthetic "digits": per-class template + noise — learnable to high
    # accuracy fast, giving confident margins like any real PTQ candidate
    # (near-tie logits would make the test measure argmax coin flips)
    templates = rng.rand(classes, 1, 28, 28).astype("float32")
    y = rng.randint(0, classes, n).astype("int64")
    x = (templates[y] + 0.3 * rng.randn(n, 1, 28, 28)).astype("float32")
    paddle.seed(2)
    model = LeNet(num_classes=classes)
    _train(model, x, y, steps=30, lr=3e-3)
    fp32_acc = _acc(model, x, y)
    assert fp32_acc > 0.9  # sanity: the target is learnable

    ptq = PostTrainingQuantization()
    ptq.prepare(model)
    model.eval()
    for i in range(0, n, 64):  # calibration passes feed the observers
        model(paddle.to_tensor(x[i:i + 64]))
    qmodel = ptq.convert(model)
    q_acc = _acc(qmodel, x, y)
    assert q_acc >= fp32_acc - 0.01, (fp32_acc, q_acc)
    # weights really are int8
    found = [b for _, b in qmodel.named_buffers() if
             b.numpy().dtype == np.int8]
    assert found, "no int8 weight buffers after convert"


def test_quantize_attribute_style_model():
    """Models whose forward resolves sublayers as attributes (`self.fc(x)`)
    must actually execute the quantized wrapper, not a stale __dict__
    reference to the fp32 layer."""
    class Net(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = paddle.nn.Linear(8, 4)

        def forward(self, x):
            return self.fc(x)

    paddle.seed(5)
    net = Net()
    qnet = ImperativeQuantAware().quantize(net)
    assert isinstance(qnet.fc, QuantedLinear)  # attribute view swapped too
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8)
                         .astype("float32"))
    qnet.train()
    out = qnet(x)
    assert float(qnet.fc.act_scale.numpy()) > 0  # observer actually ran


def test_qat_eval_before_any_training_is_identity():
    """Unobserved activation scale must behave as identity, not saturate
    everything to the epsilon floor."""
    paddle.seed(6)
    lin = paddle.nn.Linear(8, 4)
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8)
                         .astype("float32"))
    ref = lin(x).numpy()
    q = QuantedLinear(lin)
    q.eval()
    out = q(x).numpy()  # weight qdq only; activations untouched
    assert np.abs(out - ref).max() < 0.05 * np.abs(ref).max() + 1e-3


def test_ptq_rejects_wide_bits():
    with pytest.raises(ValueError):
        Int8Linear(paddle.nn.Linear(4, 4), bits=16)


def test_qat_per_tensor_weight_quant_option():
    paddle.seed(7)
    model = _mlp()
    q = ImperativeQuantAware(weight_quantize_type="abs_max").quantize(model)
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 16)
                         .astype("float32"))
    q.train()
    assert np.isfinite(q(x).numpy()).all()


def test_ptq_int8_linear_numerics():
    paddle.seed(3)
    lin = paddle.nn.Linear(8, 4)
    x = paddle.to_tensor(np.random.RandomState(1).randn(5, 8)
                         .astype("float32"))
    ref = lin(x).numpy()
    q = Int8Linear(lin)
    out = q(x).numpy()
    # per-channel int8 weight quant: ~1/127 relative error budget
    assert np.abs(out - ref).max() < 0.05 * np.abs(ref).max() + 1e-3
