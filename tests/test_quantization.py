"""Quantization: QAT fake-quant + PTQ int8 conversion.

Reference test strategy: slim's test_imperative_qat.py trains a small conv
net with ImperativeQuantAware and checks the quantized model tracks fp32
accuracy; test_post_training_quantization_* calibrate then compare."""
import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.quantization import (
    fake_quantize_dequantize_abs_max,
    fake_quantize_dequantize_channel_wise_abs_max,
    ImperativeQuantAware, PostTrainingQuantization, Int8Linear,
    Int8WeightOnlyLinear, QuantedLinear, QuantedConv2D,
    quantize_for_serving, quantize_weight_int8)


def test_fake_qdq_values_on_grid_and_ste_grad():
    x = paddle.to_tensor(np.linspace(-2, 2, 31).astype("float32"))
    x.stop_gradient = False
    y = fake_quantize_dequantize_abs_max(x, bits=8)
    # quantized values live on the 8-bit grid scaled by absmax
    step = 2.0 / 127
    np.testing.assert_allclose(y.numpy() / step,
                               np.round(y.numpy() / step), atol=1e-5)
    np.testing.assert_allclose(y.numpy(), x.numpy(), atol=step)
    # STE: gradient is identity
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), np.ones(31, "float32"),
                               atol=1e-6)


def test_channel_wise_scales():
    w = paddle.to_tensor(
        (np.random.RandomState(0).randn(4, 8) *
         np.array([0.1, 1.0, 10.0, 100.0])[:, None]).astype("float32"))
    q = fake_quantize_dequantize_channel_wise_abs_max(w, quant_axis=0)
    # each row keeps ~8-bit relative resolution despite 1000x range spread
    rel = np.abs(q.numpy() - w.numpy()) / np.abs(w.numpy()).max(1, keepdims=True)
    assert rel.max() < 1.0 / 127


def _blob_data(n=512, d=16, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    teacher = rng.randn(d, classes).astype("float32")
    x = rng.randn(n, d).astype("float32")
    y = (x @ teacher).argmax(1).astype("int64")
    return x, y


def _mlp(d=16, classes=4):
    return paddle.nn.Sequential(
        paddle.nn.Linear(d, 32), paddle.nn.ReLU(),
        paddle.nn.Linear(32, classes))


def _train(model, x, y, steps=60, lr=5e-2):
    opt = paddle.optimizer.Adam(learning_rate=lr,
                                parameters=model.parameters())
    crit = paddle.nn.CrossEntropyLoss()
    xt, yt = paddle.to_tensor(x), paddle.to_tensor(y)
    for _ in range(steps):
        loss = crit(model(xt), yt)
        loss.backward()
        opt.step()
        opt.clear_grad()
    return float(loss)


def _acc(model, x, y):
    model.eval()
    logits = model(paddle.to_tensor(x)).numpy()
    return float((logits.argmax(1) == y).mean())


def test_qat_trains_and_tracks_fp32_accuracy():
    x, y = _blob_data()
    paddle.seed(0)
    model = _mlp()
    _train(model, x, y, steps=40)
    fp32_acc = _acc(model, x, y)

    qat = ImperativeQuantAware()
    qmodel = qat.quantize(model)
    assert isinstance(qmodel[0], QuantedLinear)  # swapped in place
    qmodel.train()
    _train(qmodel, x, y, steps=30)  # finetune with fake quant in the graph
    q_acc = _acc(qmodel, x, y)
    assert q_acc >= fp32_acc - 0.01, (fp32_acc, q_acc)
    # observers populated
    assert float(qmodel[0].act_scale.numpy()) > 0


def test_qat_save_quantized_model_roundtrip(tmp_path):
    x, y = _blob_data(n=64)
    paddle.seed(1)
    model = _mlp()
    qat = ImperativeQuantAware()
    qmodel = qat.quantize(model)
    qmodel.train()
    _train(qmodel, x, y, steps=5)
    qmodel.eval()
    ref = qmodel(paddle.to_tensor(x)).numpy()
    path = str(tmp_path / "qat_model")
    qat.save_quantized_model(
        qmodel, path,
        input_spec=[paddle.static.InputSpec([64, 16], "float32")])
    loaded = paddle.jit.load(path)
    out = loaded(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_ptq_lenet_within_one_percent():
    """PTQ'd conv net must stay within 1% of the fp32 accuracy
    (VERDICT r1 'done' bar for quantization)."""
    from paddle_tpu.vision.models import LeNet
    rng = np.random.RandomState(0)
    n, classes = 256, 10
    # synthetic "digits": per-class template + noise — learnable to high
    # accuracy fast, giving confident margins like any real PTQ candidate
    # (near-tie logits would make the test measure argmax coin flips)
    templates = rng.rand(classes, 1, 28, 28).astype("float32")
    y = rng.randint(0, classes, n).astype("int64")
    x = (templates[y] + 0.3 * rng.randn(n, 1, 28, 28)).astype("float32")
    paddle.seed(2)
    model = LeNet(num_classes=classes)
    _train(model, x, y, steps=30, lr=3e-3)
    fp32_acc = _acc(model, x, y)
    assert fp32_acc > 0.9  # sanity: the target is learnable

    ptq = PostTrainingQuantization()
    ptq.prepare(model)
    model.eval()
    for i in range(0, n, 64):  # calibration passes feed the observers
        model(paddle.to_tensor(x[i:i + 64]))
    qmodel = ptq.convert(model)
    q_acc = _acc(qmodel, x, y)
    assert q_acc >= fp32_acc - 0.01, (fp32_acc, q_acc)
    # weights really are int8
    found = [b for _, b in qmodel.named_buffers() if
             b.numpy().dtype == np.int8]
    assert found, "no int8 weight buffers after convert"


def test_quantize_attribute_style_model():
    """Models whose forward resolves sublayers as attributes (`self.fc(x)`)
    must actually execute the quantized wrapper, not a stale __dict__
    reference to the fp32 layer."""
    class Net(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = paddle.nn.Linear(8, 4)

        def forward(self, x):
            return self.fc(x)

    paddle.seed(5)
    net = Net()
    qnet = ImperativeQuantAware().quantize(net)
    assert isinstance(qnet.fc, QuantedLinear)  # attribute view swapped too
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8)
                         .astype("float32"))
    qnet.train()
    out = qnet(x)
    assert float(qnet.fc.act_scale.numpy()) > 0  # observer actually ran


def test_qat_eval_before_any_training_is_identity():
    """Unobserved activation scale must behave as identity, not saturate
    everything to the epsilon floor."""
    paddle.seed(6)
    lin = paddle.nn.Linear(8, 4)
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8)
                         .astype("float32"))
    ref = lin(x).numpy()
    q = QuantedLinear(lin)
    q.eval()
    out = q(x).numpy()  # weight qdq only; activations untouched
    assert np.abs(out - ref).max() < 0.05 * np.abs(ref).max() + 1e-3


def test_ptq_rejects_wide_bits():
    with pytest.raises(ValueError):
        Int8Linear(paddle.nn.Linear(4, 4), bits=16)


def test_qat_per_tensor_weight_quant_option():
    paddle.seed(7)
    model = _mlp()
    q = ImperativeQuantAware(weight_quantize_type="abs_max").quantize(model)
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 16)
                         .astype("float32"))
    q.train()
    assert np.isfinite(q(x).numpy()).all()


def test_ptq_int8_linear_numerics():
    paddle.seed(3)
    lin = paddle.nn.Linear(8, 4)
    x = paddle.to_tensor(np.random.RandomState(1).randn(5, 8)
                         .astype("float32"))
    ref = lin(x).numpy()
    q = Int8Linear(lin)
    out = q(x).numpy()
    # per-channel int8 weight quant: ~1/127 relative error budget
    assert np.abs(out - ref).max() < 0.05 * np.abs(ref).max() + 1e-3


# ---------------------------------------------------------------------------
# int8 weight-only serving path (ISSUE-7)
# ---------------------------------------------------------------------------

def _tiny_gpt(seed=7):
    from paddle_tpu import models
    cfg = models.GPTConfig(vocab_size=13, hidden_size=16,
                           num_hidden_layers=2, num_attention_heads=2,
                           hidden_dropout_prob=0.0,
                           attention_probs_dropout_prob=0.0,
                           max_position_embeddings=64)
    paddle.seed(seed)
    m = models.GPTForPretraining(cfg)
    m.eval()
    return m


def test_int8_roundtrip_weight_error_bound():
    """quantize -> dequant round-trip error is bounded by half a grid
    step PER CHANNEL: |w - w_int8*scale| <= scale/2 elementwise."""
    rng = np.random.RandomState(0)
    w = (rng.randn(32, 16) * np.logspace(-2, 1, 16)[None, :]) \
        .astype("float32")
    wi, scale = quantize_weight_int8(w, per_channel=True, axis=1)
    assert np.asarray(wi).dtype == np.int8
    deq = np.asarray(wi).astype(np.float32) * np.asarray(scale)
    err = np.abs(deq - w)
    assert (err <= np.asarray(scale) / 2 + 1e-8).all(), err.max()


def test_int8_per_channel_beats_per_tensor_on_spread_weights():
    """With a 1000x per-channel magnitude spread, per-channel scales keep
    ~8-bit resolution in every column; the single per-tensor scale
    crushes the small columns — the reason the serving path defaults to
    per-channel."""
    rng = np.random.RandomState(1)
    w = (rng.randn(64, 8) * np.array([0.01, 0.05, 0.1, 0.5, 1, 2, 5, 10])
         [None, :]).astype("float32")
    wi_c, s_c = quantize_weight_int8(w, per_channel=True, axis=1)
    wi_t, s_t = quantize_weight_int8(w, per_channel=False)
    err_c = np.abs(np.asarray(wi_c).astype(np.float32) * np.asarray(s_c)
                   - w).max()
    err_t = np.abs(np.asarray(wi_t).astype(np.float32) * np.asarray(s_t)
                   - w).max()
    # worst-channel relative error: per-channel stays on the 1/254 grid
    rel_c = np.abs(np.asarray(wi_c).astype(np.float32) * np.asarray(s_c)
                   - w) / np.abs(w).max(0, keepdims=True)
    assert rel_c.max() < 1.0 / 127
    assert err_c <= err_t + 1e-8
    # per-tensor destroys the smallest column's resolution
    small = np.abs(np.asarray(wi_t).astype(np.float32) * np.asarray(s_t)
                   - w)[:, 0].max() / np.abs(w[:, 0]).max()
    assert small > 1.0 / 127


def test_quanted_linear_matches_imperative_quant_aware_on_mlp():
    """ImperativeQuantAware.quantize must be exactly 'wrap every Linear
    in QuantedLinear': hand-wrapping a tiny MLP layer-by-layer produces
    the same outputs as the driver."""
    paddle.seed(4)
    mlp = _mlp()
    x = paddle.to_tensor(np.random.RandomState(0).randn(6, 16)
                         .astype("float32"))
    paddle.seed(4)
    ref_model = _mlp()  # identical weights (same seed)
    hand = paddle.nn.Sequential(
        QuantedLinear(ref_model[0]), paddle.nn.ReLU(),
        QuantedLinear(ref_model[2]))
    auto = ImperativeQuantAware().quantize(mlp)
    hand.eval()
    auto.eval()
    np.testing.assert_allclose(hand(x).numpy(), auto(x).numpy(),
                               rtol=1e-6, atol=1e-6)


def test_quantize_for_serving_swaps_linears_and_bounds_logit_error():
    m = _tiny_gpt()
    ids = paddle.to_tensor(np.random.RandomState(0)
                           .randint(0, 13, (2, 8)).astype(np.int32))
    ref = m(ids).numpy()
    qm = quantize_for_serving(m)
    assert qm is m  # in place
    assert isinstance(qm.gpt.blocks[0].qkv, Int8WeightOnlyLinear)
    out = qm(ids).numpy()
    assert np.abs(out - ref).max() < 0.05 * np.abs(ref).max() + 1e-3
    # weights really live as int8 buffers (-> compiled-program state and
    # jit.save artifacts hold int8)
    int8_keys = [k for k, v in qm.state_dict().items()
                 if v.numpy().dtype == np.int8]
    assert len(int8_keys) == 2 * 4  # 2 blocks x (qkv, proj, ffn_in, ffn_out)
    with pytest.raises(ValueError):
        quantize_for_serving(_tiny_gpt(), quantize="int4")


def test_quantized_serving_stream_matches_quantized_solo():
    """enable_serving(..., quantize='int8') end-to-end: the engine's
    greedy stream is bit-identical to solo generate of the SAME quantized
    model, with no new programs beyond the quantized set."""
    from paddle_tpu.inference import Config, create_predictor
    cfg = Config()
    cfg.enable_serving(model=_tiny_gpt(), quantize="int8", max_slots=2,
                       max_len=48, prefill_buckets=(8,), start=False)
    pred = create_predictor(cfg)
    try:
        qm = pred.engine.model  # the quantized layer tree
        assert isinstance(qm.gpt.blocks[0].qkv, Int8WeightOnlyLinear)
        r = pred.submit([1, 2, 3, 4], max_new_tokens=6)
        pred.engine.run_until_drained(timeout=120)
        out, _ = qm.generate(paddle.to_tensor(
            np.asarray([1, 2, 3, 4], np.int32)[None]), max_new_tokens=6)
        assert r.tokens() == np.asarray(out.numpy())[0].tolist()
        cc = pred.engine.compile_counts()
        assert cc["total"] <= cc["bound"]
    finally:
        pred.close()


def test_quantized_jit_save_artifact_roundtrip(tmp_path):
    """jit.save of a quantized model stores int8 weights + fp scales in
    the .pdiparams.npz; restoring them into a fresh quantized skeleton
    reproduces the outputs exactly."""
    qm = quantize_for_serving(_tiny_gpt(seed=5))
    ids = paddle.to_tensor(np.random.RandomState(1)
                           .randint(0, 13, (2, 6)).astype(np.int32))
    ref = qm(ids).numpy()
    path = str(tmp_path / "qgpt")
    paddle.jit.save(qm, path)
    data = np.load(path + ".pdiparams.npz")
    assert sum(1 for k in data.files if data[k].dtype == np.int8) == 8
    fresh = quantize_for_serving(_tiny_gpt(seed=99))  # different weights
    fresh.set_state_dict({k: data[k] for k in data.files})
    np.testing.assert_array_equal(fresh(ids).numpy(), ref)


def test_int8_dequant_matmul_pallas_interpret_parity():
    """The TPU dequant-matmul kernel (via the pallas interpreter) must
    match the XLA fallback bit-for-bit on aligned shapes, including the
    M-padding path."""
    from paddle_tpu.ops import int8_matmul
    rng = np.random.RandomState(0)
    for m, k, n in [(5, 32, 128), (16, 64, 256), (300, 32, 128)]:
        x = jnp.asarray(rng.randn(m, k).astype(np.float32))
        wi, s = quantize_weight_int8(rng.randn(k, n).astype("float32"))
        ref = int8_matmul.dequant_matmul(x, wi, s.reshape(1, -1))
        int8_matmul._INTERPRET = True
        try:
            out = int8_matmul.dequant_matmul(x, wi, s.reshape(1, -1))
        finally:
            int8_matmul._INTERPRET = False
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-6, atol=1e-6)
