"""Book-tier end-to-end convergence suite.

Reference: python/paddle/fluid/tests/book/ (test_recognize_digits.py:1,
test_fit_a_line.py, test_word2vec.py, test_machine_translation.py) — each
trains a model to an ABSOLUTE metric threshold, then round-trips through
save/load-inference and checks the served output.  Zero-egress stand-in
data: deterministic synthetic datasets with enough structure that the model
must genuinely learn (class prototypes + noise for digits, a linear ground
truth for fit_a_line, an n-gram language for word2vec, string reversal for
the seq2seq translation task).
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
import paddle_tpu.nn.functional as F

pytestmark = pytest.mark.slow


# ---------------------------------------------------------------------------
# synthetic data


def synth_digits(n, rng, noise=0.35):
    """10 fixed 28x28 prototypes + gaussian noise -> (x, y)."""
    protos = np.stack([np.outer(
        np.sin(np.linspace(0, (c + 2) * np.pi / 3, 28)),
        np.cos(np.linspace(0, (c % 5 + 1) * np.pi, 28)))
        for c in range(10)]).astype("float32")
    y = rng.randint(0, 10, n)
    x = protos[y] + rng.randn(n, 28, 28).astype("float32") * noise
    return x[:, None], y.astype("int64")


# ---------------------------------------------------------------------------
# 1. recognize_digits (reference book test_recognize_digits.py:1)


def test_book_recognize_digits_lenet(tmp_path):
    from paddle_tpu.vision.models import LeNet
    rng = np.random.RandomState(0)
    paddle.seed(0)
    model = LeNet(num_classes=10)
    opt = paddle.optimizer.Adam(learning_rate=2e-3,
                                parameters=model.parameters())
    xtr, ytr = synth_digits(1024, rng)
    xte, yte = synth_digits(256, rng)
    for epoch in range(3):
        perm = rng.permutation(len(xtr))
        for i in range(0, len(xtr), 64):
            idx = perm[i:i + 64]
            logits = model(paddle.to_tensor(xtr[idx]))
            loss = F.cross_entropy(logits, paddle.to_tensor(ytr[idx]))
            loss.backward(); opt.step(); opt.clear_grad()
    model.eval()
    pred = model(paddle.to_tensor(xte)).numpy().argmax(-1)
    acc = (pred == yte).mean()
    assert acc >= 0.9, f"LeNet accuracy {acc} below book threshold"

    # save/load-inference round trip (the book tests' second half)
    path = os.path.join(tmp_path, "digits")
    paddle.jit.save(model, path,
                    input_spec=[paddle.static.InputSpec([None, 1, 28, 28])])
    served = paddle.jit.load(path)
    out = served(paddle.to_tensor(xte[:8]))
    np.testing.assert_allclose(out.numpy(),
                               model(paddle.to_tensor(xte[:8])).numpy(),
                               rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# 2. fit_a_line (reference book test_fit_a_line.py)


def test_book_fit_a_line(tmp_path):
    rng = np.random.RandomState(1)
    paddle.seed(1)
    w_true = rng.randn(13).astype("float32")
    x = rng.randn(512, 13).astype("float32")
    y = x @ w_true + 0.7 + rng.randn(512).astype("float32") * 0.05

    model = nn.Linear(13, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=model.parameters())
    for step in range(200):
        i = (step * 64) % 448
        xb = paddle.to_tensor(x[i:i + 64])
        yb = paddle.to_tensor(y[i:i + 64, None])
        loss = F.mse_loss(model(xb), yb)
        loss.backward(); opt.step(); opt.clear_grad()
    final = float(F.mse_loss(model(paddle.to_tensor(x)),
                             paddle.to_tensor(y[:, None])))
    assert final < 0.02, f"fit_a_line cost {final} above book threshold"

    path = os.path.join(tmp_path, "line")
    paddle.jit.save(model, path,
                    input_spec=[paddle.static.InputSpec([None, 13])])
    served = paddle.jit.load(path)
    np.testing.assert_allclose(served(paddle.to_tensor(x[:4])).numpy(),
                               model(paddle.to_tensor(x[:4])).numpy(),
                               rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# 3. word2vec (reference book test_word2vec.py: n-gram LM over embeddings)


class NGram(nn.Layer):
    def __init__(self, vocab, emb=32, hid=64, n=4):
        super().__init__()
        self.emb = nn.Embedding(vocab, emb)
        self.fc1 = nn.Linear(emb * n, hid)
        self.fc2 = nn.Linear(hid, vocab)

    def forward(self, ctx):  # (B, n)
        e = self.emb(ctx)
        b = e.shape[0]
        h = F.tanh(self.fc1(e.reshape([b, -1])))
        return self.fc2(h)


def test_book_word2vec(tmp_path):
    # deterministic markov "language": word (i) is followed by one of
    # {2i, 2i+1} mod V — an n-gram model must drive cost well below log(V)
    V, n = 50, 4
    rng = np.random.RandomState(2)
    paddle.seed(2)
    seq = [0]
    for _ in range(4000):
        seq.append((2 * seq[-1] + rng.randint(2)) % V)
    seq = np.asarray(seq)
    ctxs = np.stack([seq[i:i + n] for i in range(len(seq) - n)])
    nxts = seq[n:]

    model = NGram(V, n=n)
    opt = paddle.optimizer.Adam(learning_rate=5e-3,
                                parameters=model.parameters())
    losses = []
    for step in range(300):
        i = (step * 128) % (len(ctxs) - 128)
        loss = F.cross_entropy(
            model(paddle.to_tensor(ctxs[i:i + 128].astype("int64"))),
            paddle.to_tensor(nxts[i:i + 128].astype("int64")))
        loss.backward(); opt.step(); opt.clear_grad()
        losses.append(float(loss))
    # ideal cost is one bit (two successors); book threshold: well under
    # the log(V) ~ 3.9 uniform baseline
    assert losses[-1] < 1.5, f"word2vec cost {losses[-1]} above threshold"

    path = os.path.join(tmp_path, "w2v")
    paddle.jit.save(model, path,
                    input_spec=[paddle.static.InputSpec([None, n], "int64")])
    served = paddle.jit.load(path)
    np.testing.assert_allclose(
        served(paddle.to_tensor(ctxs[:4].astype("int64"))).numpy(),
        model(paddle.to_tensor(ctxs[:4].astype("int64"))).numpy(),
        rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# 4. machine translation: seq2seq + BeamSearchDecoder decode
#    (reference book test_machine_translation.py: encoder-decoder with
#    beam search over operators/math/beam_search.cc)


class Seq2Seq(nn.Layer):
    def __init__(self, vocab, hid=64):
        super().__init__()
        self.src_emb = nn.Embedding(vocab, hid)
        self.tgt_emb = nn.Embedding(vocab, hid)
        self.encoder = nn.GRU(hid, hid)
        self.cell = nn.GRUCell(hid, hid)
        self.proj = nn.Linear(hid, vocab)

    def encode(self, src):
        _, h = self.encoder(self.src_emb(src))
        return h[0]  # (B, hid)

    def forward(self, src, tgt_in):
        h = self.encode(src)
        outs = []
        for t in range(tgt_in.shape[1]):
            o, h = self.cell(self.tgt_emb(tgt_in[:, t]), h)
            outs.append(self.proj(o))
        import paddle_tpu.tensor as T
        return T.stack(outs, axis=1)


def test_book_machine_translation_beam_decode():
    """Train tiny seq2seq to reverse digit strings, then decode with
    BeamSearchDecoder/dynamic_decode and check exact-match translations."""
    V, L = 12, 5          # tokens 3..11 payload; 0 pad / 1 bos / 2 eos
    rng = np.random.RandomState(3)
    paddle.seed(3)

    def sample_batch(b):
        src = rng.randint(3, V, (b, L))
        tgt = src[:, ::-1]
        tgt_in = np.concatenate([np.full((b, 1), 1), tgt], 1)
        tgt_out = np.concatenate([tgt, np.full((b, 1), 2)], 1)
        return (src.astype("int64"), tgt_in.astype("int64"),
                tgt_out.astype("int64"))

    model = Seq2Seq(V)
    opt = paddle.optimizer.Adam(learning_rate=5e-3,
                                parameters=model.parameters())
    from paddle_tpu.jit import TrainStep
    step_fn = TrainStep(
        model, lambda logits, label: F.cross_entropy(
            logits.reshape([-1, V]), label.reshape([-1])), opt)
    for step in range(900):
        src, tin, tout = sample_batch(32)
        step_fn(paddle.to_tensor(src), paddle.to_tensor(tin),
                paddle.to_tensor(tout))

    model.eval()
    src, _, _ = sample_batch(16)
    h0 = model.encode(paddle.to_tensor(src))
    dec = nn.BeamSearchDecoder(model.cell, start_token=1, end_token=2,
                               beam_size=3, embedding_fn=model.tgt_emb,
                               output_fn=model.proj)
    outs, _ = nn.dynamic_decode(dec, inits=h0, max_step_num=L + 1)
    best = outs.numpy()[:, :, 0]  # (B, T) best beam
    want = src[:, ::-1]
    match = sum(
        1 for i in range(16)
        if best[i, :L].tolist() == want[i].tolist()) / 16.0
    assert match >= 0.8, f"translation exact-match {match} below threshold"
