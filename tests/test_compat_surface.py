"""API-surface compat: fluid top-level names, static-graph shims, metric
ops (mean_iou / chunk_eval), distribution additions, worker info
(reference: python/paddle/__init__.py + static/ + metric/metrics.py +
distribution.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import metric as M
from paddle_tpu import static


def test_top_level_fluid_names():
    x = paddle.to_tensor(np.arange(6).reshape(2, 3).astype("float32"))
    y = paddle.to_tensor(np.ones((3, 2), "float32"))
    assert paddle.tensordot(x, y, axes=1).shape == [2, 2]
    assert not bool(paddle.has_nan(x))
    assert bool(paddle.has_inf(x / paddle.to_tensor(0.0)))
    assert float(paddle.reduce_sum(x)) == 15.0
    assert paddle.reduce_mean(x, dim=0).shape == [3]
    assert float(paddle.elementwise_mod(
        paddle.to_tensor(7), paddle.to_tensor(4))) == 3.0
    assert paddle.fill_constant([2], "int64", 5).numpy().tolist() == [5, 5]
    assert paddle.VarBase is paddle.Tensor
    assert isinstance(paddle.LoDTensorArray([1, 2]), list)
    spec = paddle.data("ids", [None, 16], "int64")
    assert spec.shape == (-1, 16)
    out = paddle.crop_tensor(paddle.to_tensor(np.ones((4, 4), "float32")),
                             shape=[2, -1], offsets=[1, 2])
    assert out.shape == [2, 2]


def test_static_shims_eager_semantics():
    with static.program_guard(static.default_main_program(),
                              static.default_startup_program()):
        with static.name_scope("blk"):
            x = paddle.to_tensor(np.full((2, 2), 2.0, "float32"),
                                 stop_gradient=False)
            loss = (x * x).sum()
    pairs = static.append_backward(loss, parameter_list=[x])
    np.testing.assert_allclose(pairs[0][1].numpy(), 4.0)

    exe = static.Executor(static.cpu_places()[0])
    out, = exe.run(fetch_list=[loss], return_numpy=True)
    assert float(out) == 16.0
    with pytest.raises(TypeError):
        exe.run(fetch_list=["a_name_string"])

    # gradients() needs a live graph (backward above released loss's tape)
    x2 = paddle.to_tensor(np.full((2, 2), 2.0, "float32"),
                          stop_gradient=False)
    loss2 = (x2 * x2).sum()
    g, = static.gradients(loss2, [x2])
    np.testing.assert_allclose(g.numpy(), 4.0)

    prog = static.CompiledProgram(static.default_main_program())
    assert prog.with_data_parallel() is prog
    scope = static.Scope()
    with static.scope_guard(scope):
        scope.var("w")
    assert "w" in scope
    y = static.py_func(lambda a: a + 1, paddle.to_tensor(1.0))
    assert float(y) == 2.0


def test_mean_iou():
    pred = paddle.to_tensor(np.array([[0, 1], [1, 1]], "int64"))
    lab = paddle.to_tensor(np.array([[0, 1], [0, 1]], "int64"))
    miou, wrong, correct = M.mean_iou(pred, lab, 2)
    np.testing.assert_allclose(float(miou), 7 / 12, rtol=1e-6)
    assert correct.numpy().tolist() == [1, 2]
    # out_wrong = union - correct (streaming iou = correct/(correct+wrong))
    assert wrong.numpy().tolist() == [1, 1]
    iou = correct.numpy() / (correct.numpy() + wrong.numpy())
    np.testing.assert_allclose(iou.mean(), float(miou), rtol=1e-6)


def test_chunk_eval_iob():
    # tags: type*2 + {0:B, 1:I}; outside = 4.  Two entity types.
    lab = np.array([[0, 1, 4, 2, 3, 4]], "int64")   # chunks A[0:1], B[3:4]
    pred = np.array([[0, 1, 4, 2, 2, 4]], "int64")  # A[0:1] correct, B wrong
    p, r, f1, ni, nl, nc = M.chunk_eval(paddle.to_tensor(pred),
                                        paddle.to_tensor(lab), "IOB", 2)
    assert int(nl) == 2 and int(nc) == 1
    assert int(ni) == 3  # pred's second B starts a new chunk
    np.testing.assert_allclose(float(p), 1 / 3)
    np.testing.assert_allclose(float(r), 1 / 2)


def test_chunk_eval_iobes_and_excluded():
    # IOBES: type*4 + {0:B,1:I,2:E,3:S}; outside = 8
    lab = np.array([[3, 8, 0, 1, 2]], "int64")   # S chunk t0, BIE chunk t0
    pred = np.array([[3, 8, 0, 1, 2]], "int64")
    p, r, f1, ni, nl, nc = M.chunk_eval(paddle.to_tensor(pred),
                                        paddle.to_tensor(lab), "IOBES", 2)
    assert int(nc) == int(nl) == int(ni) == 2 and float(f1) == 1.0
    p, r, f1, ni, nl, nc = M.chunk_eval(
        paddle.to_tensor(pred), paddle.to_tensor(lab), "IOBES", 2,
        excluded_chunk_types=[0])
    assert int(nl) == 0 and float(f1) == 0.0


def test_distribution_additions():
    from paddle_tpu.distribution import MultivariateNormalDiag, sampling_id
    loc = paddle.to_tensor(np.array([1.0, -1.0], "float32"))
    scale = paddle.to_tensor(np.array([0.5, 2.0], "float32"))
    d = MultivariateNormalDiag(loc, scale)
    s = d.sample([64], seed=3)
    assert list(s.shape) == [64, 2]
    # log_prob against scipy-free formula
    v = np.array([1.0, -1.0], "float32")
    want = -0.5 * 2 * np.log(2 * np.pi) - np.log(0.5 * 2.0)
    np.testing.assert_allclose(float(d.log_prob(paddle.to_tensor(v))),
                               want, rtol=1e-5)
    d2 = MultivariateNormalDiag(loc, scale)
    np.testing.assert_allclose(float(d.kl_divergence(d2)[()] if
                                     d.kl_divergence(d2).ndim else
                                     d.kl_divergence(d2)), 0.0, atol=1e-6)
    probs = paddle.to_tensor(np.array([[0, 0, 1.0], [1.0, 0, 0]], "float32"))
    ids = sampling_id(probs).numpy()
    assert ids.tolist() == [2, 0]


def test_worker_info_in_workers():
    """get_worker_info: None in main process; populated inside workers."""
    import paddle_tpu.io as io
    assert io.get_worker_info() is None

    class DS:
        def __len__(self):
            return 8

        def __getitem__(self, i):
            info = io.get_worker_info()
            assert info is not None and info.num_workers == 2
            return np.float32(info.id)

    seen = set()
    for batch in io.DataLoader(DS(), batch_size=2, num_workers=2):
        seen.update(np.asarray(batch.numpy()).reshape(-1).tolist())
    assert seen.issubset({0.0, 1.0}) and seen
