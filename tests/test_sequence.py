"""Sequence/ragged toolkit (the LoD answer) — VERDICT r1 missing #8.

Reference: operators/sequence_ops/* semantics checked against numpy
references; packed-sequence masking checked against per-sequence attention
through the flash kernel (interpret mode)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.text import pack_sequences, BucketByLengthBatchSampler


def _ragged(seed=0, b=4, tmax=6, h=3):
    rng = np.random.RandomState(seed)
    lengths = rng.randint(1, tmax + 1, b).astype("int64")
    x = rng.randn(b, tmax, h).astype("float32")
    return x, lengths


def test_sequence_pad_unpad_roundtrip():
    x, lengths = _ragged()
    padded = paddle.to_tensor(x)
    packed = F.sequence_unpad(padded, paddle.to_tensor(lengths))
    assert packed.shape[0] == int(lengths.sum())
    repad = F.sequence_pad(packed, paddle.to_tensor(lengths),
                           maxlen=x.shape[1])
    mask = np.arange(x.shape[1])[None] < lengths[:, None]
    np.testing.assert_allclose(repad.numpy()[mask], x[mask])
    assert (repad.numpy()[~mask] == 0).all()


def test_sequence_pool_modes():
    x, lengths = _ragged(1)
    lt = paddle.to_tensor(lengths)
    xt = paddle.to_tensor(x)
    for mode in ("sum", "average", "sqrt", "max", "min", "last", "first"):
        got = F.sequence_pool(xt, lt, mode).numpy()
        for b, n in enumerate(lengths):
            seg = x[b, :n]
            ref = {"sum": seg.sum(0), "average": seg.mean(0),
                   "sqrt": seg.sum(0) / np.sqrt(n), "max": seg.max(0),
                   "min": seg.min(0), "last": seg[-1], "first": seg[0]}[mode]
            np.testing.assert_allclose(got[b], ref, rtol=1e-5, atol=1e-6)


def test_sequence_softmax_and_reverse():
    x, lengths = _ragged(2)
    lt = paddle.to_tensor(lengths)
    sm = F.sequence_softmax(paddle.to_tensor(x), lt).numpy()
    rv = F.sequence_reverse(paddle.to_tensor(x), lt).numpy()
    for b, n in enumerate(lengths):
        ref = np.exp(x[b, :n] - x[b, :n].max(0))
        np.testing.assert_allclose(sm[b, :n], ref / ref.sum(0), rtol=1e-4,
                                   atol=1e-5)
        assert (sm[b, n:] == 0).all()
        np.testing.assert_allclose(rv[b, :n], x[b, :n][::-1])
        np.testing.assert_allclose(rv[b, n:], x[b, n:])  # padding untouched


def test_sequence_softmax_grad_masked():
    x, lengths = _ragged(3)
    xt = paddle.to_tensor(x)
    xt.stop_gradient = False
    out = F.sequence_softmax(xt, paddle.to_tensor(lengths))
    out.sum().backward()
    g = xt.grad.numpy()
    for b, n in enumerate(lengths):
        assert np.abs(g[b, n:]).max() == 0  # no grad leaks into padding


def test_sequence_concat_and_enumerate_and_expand():
    x1, l1 = _ragged(4, tmax=4)
    x2, l2 = _ragged(5, tmax=5)
    out, lens = F.sequence_concat(
        [paddle.to_tensor(x1), paddle.to_tensor(x2)],
        [paddle.to_tensor(l1), paddle.to_tensor(l2)])
    on = out.numpy()
    for b in range(x1.shape[0]):
        ref = np.concatenate([x1[b, :l1[b]], x2[b, :l2[b]]])
        np.testing.assert_allclose(on[b, :l1[b] + l2[b]], ref, rtol=1e-6)
    np.testing.assert_array_equal(lens.numpy(), l1 + l2)

    ids = paddle.to_tensor(np.arange(12).reshape(2, 6).astype("int32"))
    win = F.sequence_enumerate(ids, 3, pad_value=-1).numpy()
    assert win.shape == (2, 6, 3)
    np.testing.assert_array_equal(win[0, 0], [0, 1, 2])
    np.testing.assert_array_equal(win[0, 5], [5, -1, -1])

    vec = paddle.to_tensor(np.arange(8).reshape(4, 2).astype("float32"))
    exp = F.sequence_expand_as(vec, paddle.to_tensor(
        np.array([2, 1, 3, 2], "int64"))).numpy()
    assert exp.shape == (4, 3, 2)
    np.testing.assert_allclose(exp[2, 2], vec.numpy()[2])
    assert (exp[1, 1:] == 0).all()


def test_pack_sequences_and_segment_attention_parity():
    """Packed rows + segment ids through the flash kernel must equal each
    sequence attended separately — the LoD packing story end-to-end."""
    from paddle_tpu.ops import flash_attention as fa
    fa._INTERPRET = True
    try:
        rng = np.random.RandomState(0)
        row_len, d, h = 128, 64, 1
        seq_lens = [50, 40, 30, 60, 128, 20]
        seqs = [np.arange(n) for n in seq_lens]
        tokens, segs, pos = pack_sequences(seqs, row_len)
        assert tokens.shape[1] == row_len
        # every sequence fully placed, position ids restart per segment
        assert sum(segs.max(1)) >= 1
        total = sum(min(n, row_len) for n in seq_lens)
        assert int((segs > 0).sum()) == total

        # attention parity on one packed row with 2 segments
        a, b = 48, 64
        q = rng.randn(1, row_len, h, d).astype("float32")
        seg = np.zeros((1, row_len), "int32")
        seg[0, :a] = 1
        seg[0, a:a + b] = 2
        st = jnp.asarray(seg)
        packed = fa.flash_attention_bshd(
            jnp.asarray(q), jnp.asarray(q), jnp.asarray(q),
            q_segment_ids=st, kv_segment_ids=st)
        naive = []
        for s, e in ((0, a), (a, a + b)):
            qs = q[:, s:e]
            sc = np.einsum("bshd,bthd->bhst", qs, qs) / np.sqrt(d)
            p = jax.nn.softmax(jnp.asarray(sc), -1)
            naive.append(np.einsum("bhst,bthd->bshd", np.asarray(p),
                                   qs))
        np.testing.assert_allclose(np.asarray(packed)[:, :a],
                                   naive[0], rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(packed)[:, a:a + b],
                                   naive[1], rtol=2e-4, atol=2e-4)
    finally:
        fa._INTERPRET = False


def test_empty_sequences_are_safe():
    """Length-0 rows: pool modes yield pad_value, softmax grads stay
    finite (the jnp.where -inf NaN-grad trap)."""
    x = paddle.to_tensor(np.random.RandomState(0).randn(3, 4, 2)
                         .astype("float32"))
    lengths = paddle.to_tensor(np.array([0, 2, 4], "int64"))
    for mode in ("max", "min", "average", "first", "last"):
        out = F.sequence_pool(x, lengths, mode, pad_value=7.0).numpy()
        np.testing.assert_allclose(out[0], 7.0)
        assert np.isfinite(out).all()
    xg = paddle.to_tensor(np.random.RandomState(1).randn(3, 4, 2)
                          .astype("float32"))
    xg.stop_gradient = False
    F.sequence_softmax(xg, lengths).sum().backward()
    assert np.isfinite(xg.grad.numpy()).all()
    assert np.abs(xg.grad.numpy()[0]).max() == 0  # empty row: zero grad


def test_sequence_enumerate_respects_lengths():
    ids = paddle.to_tensor(np.arange(12).reshape(2, 6).astype("int32"))
    lengths = paddle.to_tensor(np.array([3, 6], "int64"))
    win = F.sequence_enumerate(ids, 2, lengths=lengths, pad_value=-1).numpy()
    np.testing.assert_array_equal(win[0, 2], [2, -1])  # past length 3
    np.testing.assert_array_equal(win[0, 3], [-1, -1])
    np.testing.assert_array_equal(win[1, 4], [10, 11])


def test_pack_sequences_rejects_overlong():
    with pytest.raises(ValueError, match="row_len"):
        pack_sequences([np.arange(200)], 128)
    toks, _, _ = pack_sequences([np.arange(200)], 128, truncate=True)
    assert toks.shape == (1, 128)


def test_bucket_sampler_len_does_not_consume_rng():
    lengths = list(np.random.RandomState(0).randint(1, 100, 37))
    a = BucketByLengthBatchSampler(lengths, [32, 64], 4, shuffle=True,
                                   seed=9)
    b = BucketByLengthBatchSampler(lengths, [32, 64], 4, shuffle=True,
                                   seed=9)
    len(a); len(a); len(a)  # must not advance the RNG
    assert list(a) == list(b)
    assert len(a) == len(list(b))


def test_bucket_sampler_groups_by_length():
    lengths = [5, 100, 7, 90, 6, 95, 8, 85]
    bs = BucketByLengthBatchSampler(lengths, bucket_boundaries=[16],
                                    batch_size=2)
    batches = list(bs)
    assert len(bs) == len(batches)
    for batch in batches:
        ls = [lengths[i] for i in batch]
        assert max(ls) <= 16 or min(ls) > 16  # no mixed buckets


def test_varlen_bert_trains_with_masked_flash_attention():
    """VERDICT r1 'done' bar: a variable-length BERT batch trains THROUGH
    the flash kernel with a padding mask (bias path) and dropout."""
    from paddle_tpu import models
    from paddle_tpu.core import op as core_op
    from paddle_tpu.ops import flash_attention as fa
    fa._INTERPRET = True
    # spy counts PYTHON calls into the kernel wrapper: the dispatch fast
    # path would trace it once and replay the compiled executable (the
    # counter is a trace-time side effect), so count on the uncached path
    prev_cache = core_op.set_dispatch_cache_enabled(False)
    calls = {"n": 0}
    orig = fa.flash_attention_bshd

    def spy(*a, **kw):
        out = orig(*a, **kw)
        if out is not None:
            calls["n"] += 1
        return out

    fa.flash_attention_bshd = spy
    try:
        paddle.seed(0)
        cfg = models.BertConfig(
            vocab_size=128, hidden_size=64, num_hidden_layers=2,
            num_attention_heads=1, intermediate_size=128,
            max_position_embeddings=128,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.1)
        model = models.BertForPretraining(cfg)
        crit = models.BertPretrainingCriterion()
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=model.parameters())
        rng = np.random.RandomState(0)
        b, s = 2, 128
        lengths = np.array([80, 128], "int64")
        ids = rng.randint(0, 128, (b, s)).astype("int32")
        labels = rng.randint(0, 128, (b, s)).astype("int32")
        # mask the loss AND attention beyond each length
        labels_m = labels.copy()
        for i, n in enumerate(lengths):
            labels_m[i, n:] = -100
        attn_mask = F.sequence_mask(paddle.to_tensor(lengths), maxlen=s,
                                    dtype="int64")
        losses = []
        for _ in range(3):
            logits, nsp = model(paddle.to_tensor(ids),
                                attention_mask=attn_mask)
            loss = crit(logits, nsp, paddle.to_tensor(labels_m))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert calls["n"] >= 6  # 2 layers x 3 steps through the kernel
        assert losses[-1] < losses[0]
    finally:
        fa.flash_attention_bshd = orig
        fa._INTERPRET = False
        core_op.set_dispatch_cache_enabled(prev_cache)
