# paddle_tpu inference from R (reference: r/example/mobilenet.r upstream).
# Usage: Rscript predictor.r <model_prefix>
library(reticulate)

args <- commandArgs(trailingOnly = TRUE)
if (length(args) < 1) stop("usage: Rscript predictor.r <model_prefix>")

np <- import("numpy")
inference <- import("paddle_tpu.inference")

config <- inference$Config(args[1])
predictor <- inference$create_predictor(config)

in_names <- predictor$get_input_names()
input_h <- predictor$get_input_handle(in_names[[1]])

x <- np$ones(c(2L, 4L), dtype = "float32")
input_h$copy_from_cpu(x)
predictor$run()

out_names <- predictor$get_output_names()
output_h <- predictor$get_output_handle(out_names[[1]])
result <- output_h$copy_to_cpu()
print(dim(result))
print(result)
