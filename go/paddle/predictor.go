// Package paddle — Go client for the paddle_tpu C inference API.
//
// Reference: go/paddle/predictor.go:1 in the upstream repo (cgo over the
// fluid inference C API).  Here the same shape wraps
// paddle_tpu/native/src/capi.cc (libpdtpu_capi.so), which embeds the
// CPython/JAX runtime behind a pure-C ABI.
//
// Build (Go toolchain not bundled in the dev image — on a host with go):
//
//	g++ -O2 -std=c++17 -shared -fPIC paddle_tpu/native/src/capi.cc \
//	    $(python3-config --includes) $(python3-config --ldflags --embed) \
//	    -o libpdtpu_capi.so
//	CGO_LDFLAGS="-L$PWD -lpdtpu_capi" go build ./go/paddle
//
// Run with PYTHONPATH pointing at the repo and LD_LIBRARY_PATH at the .so.
package paddle

/*
#cgo LDFLAGS: -lpdtpu_capi
#include <stdint.h>
#include <stdlib.h>

extern int PD_Init(void);
extern void PD_Finalize(void);
extern void* PD_CreatePredictor(const char* model_prefix);
extern int PD_PredictorRun(void* h, const float* in, const int64_t* shape,
                           int ndim, float* out, int64_t cap,
                           int64_t* out_shape, int* out_ndim);
extern void PD_DeletePredictor(void* h);
extern const char* PD_GetLastError(void);
*/
import "C"

import (
	"errors"
	"runtime"
	"unsafe"
)

// Init starts the embedded runtime (idempotent). Must be called once
// before NewPredictor.
func Init() error {
	if C.PD_Init() != 0 {
		return lastError("PD_Init")
	}
	return nil
}

// Finalize tears the embedded runtime down.
func Finalize() { C.PD_Finalize() }

func lastError(where string) error {
	return errors.New(where + ": " + C.GoString(C.PD_GetLastError()))
}

// Predictor serves a paddle_tpu jit.save artifact (model_prefix.pdmodel +
// .pdiparams.npz), mirroring the reference Predictor API surface.
type Predictor struct {
	handle unsafe.Pointer
}

// NewPredictor loads the artifact saved by paddle_tpu.jit.save(prefix).
func NewPredictor(modelPrefix string) (*Predictor, error) {
	cs := C.CString(modelPrefix)
	defer C.free(unsafe.Pointer(cs))
	h := C.PD_CreatePredictor(cs)
	if h == nil {
		return nil, lastError("PD_CreatePredictor")
	}
	p := &Predictor{handle: h}
	runtime.SetFinalizer(p, func(p *Predictor) { p.Delete() })
	return p, nil
}

// Delete releases the predictor (also installed as a finalizer).
func (p *Predictor) Delete() {
	if p.handle != nil {
		C.PD_DeletePredictor(p.handle)
		p.handle = nil
	}
}

// Run feeds one float32 input of the given shape and returns the first
// float32 output with its shape.
func (p *Predictor) Run(input []float32, shape []int64) ([]float32, []int64, error) {
	if p.handle == nil {
		return nil, nil, errors.New("predictor deleted")
	}
	outCap := int64(1 << 24) // 16M floats; grow for larger heads
	out := make([]float32, outCap)
	outShape := make([]int64, 8)
	var outNDim C.int
	rc := C.PD_PredictorRun(p.handle,
		(*C.float)(unsafe.Pointer(&input[0])),
		(*C.int64_t)(unsafe.Pointer(&shape[0])), C.int(len(shape)),
		(*C.float)(unsafe.Pointer(&out[0])), C.int64_t(outCap),
		(*C.int64_t)(unsafe.Pointer(&outShape[0])), &outNDim)
	if rc != 0 {
		return nil, nil, lastError("PD_PredictorRun")
	}
	n := int64(1)
	dims := make([]int64, int(outNDim))
	for i := range dims {
		dims[i] = outShape[i]
		n *= dims[i]
	}
	return out[:n], dims, nil
}
